"""Weight-only int8 serving quantization (models/quant.py).

The semantics contract: running the engine (or scanned generate) on
``quantize_params(p)`` is BIT-IDENTICAL to running it on the offline
dequantized view ``dequantize_params(quantize_params(p))`` — quantization
error is a property of the weights, never of where the dequant runs. The
quality contract is separate and looser (int8 is an approximation).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubetorch_tpu.models.generate import generate
from kubetorch_tpu.models.llama import LlamaConfig, llama_init
from kubetorch_tpu.models.quant import (QKEY, dequantize_params, is_quantized,
                                        quantize_params, quantized_bytes)
from kubetorch_tpu.serve import GenerationEngine

pytestmark = [pytest.mark.level("unit"), pytest.mark.slow]


@pytest.fixture(scope="module")
def fp():
    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return params, cfg


class TestQuantization:
    def test_leaf_selection_and_roundtrip_error(self, fp):
        params, cfg = fp
        q = quantize_params(params)
        # matmul weights quantized; norms/router/embed untouched
        assert is_quantized(q["layers"]["wq"])
        assert is_quantized(q["layers"]["w_down"])
        assert is_quantized(q["lm_head"])
        assert not is_quantized(q["layers"]["attn_norm"])
        assert not is_quantized(q["embed"])
        assert q["layers"]["wq"][QKEY].dtype == jnp.int8
        # per-channel symmetric int8: relative error bounded by one step
        w = np.asarray(params["layers"]["wq"], np.float32)
        dq = np.asarray(dequantize_params(q, jnp.float32)["layers"]["wq"])
        scale = np.abs(w).max(axis=-2, keepdims=True) / 127.0
        assert np.all(np.abs(w - dq) <= scale * 0.5 + 1e-8)

    def test_footprint_shrinks(self, fp):
        params, cfg = fp
        sizes = quantized_bytes(quantize_params(params))
        full = sum(np.asarray(x).nbytes for x in jax.tree.leaves(params))
        assert sizes["quantized"] + sizes["full"] < full  # int8 + scales < fp32

    def test_engine_in_graph_dequant_is_exact(self, fp):
        """engine(qparams) == engine(dequantize(qparams)) token-for-token —
        the in-graph dequant introduces no error beyond quantization."""
        params, cfg = fp
        q = quantize_params(params)
        dq = dequantize_params(q, cfg.dtype)
        prompts = [[5, 17, 42], [9, 8]]

        def run(p):
            eng = GenerationEngine(p, cfg, slots=2, max_len=32,
                                   prefill_buckets=(4,))
            hs = [eng.submit(pr, max_new_tokens=6) for pr in prompts]
            while eng.step():
                pass
            return [h.result(timeout=0) for h in hs]

        assert run(q) == run(dq)

    def test_generate_scanned_path_accepts_qparams(self, fp):
        params, cfg = fp
        q = quantize_params(params)
        dq = dequantize_params(q, cfg.dtype)
        out_q = np.asarray(generate(q, jnp.asarray([[3, 4, 5]], jnp.int32),
                                    cfg, max_new_tokens=5))
        out_dq = np.asarray(generate(dq, jnp.asarray([[3, 4, 5]], jnp.int32),
                                     cfg, max_new_tokens=5))
        assert (out_q == out_dq).all()

    def test_quality_stays_close_to_fp(self, fp):
        """Loose quality bar: int8 logits correlate strongly with fp32 on
        the first sampled position (tiny random-weight model — real models
        degrade less)."""
        from kubetorch_tpu.models.generate import forward_with_cache, init_cache

        params, cfg = fp
        q = quantize_params(params)
        toks = jnp.asarray([[5, 17, 42, 7]], jnp.int32)
        lf, _ = forward_with_cache(params, toks, init_cache(cfg, 1, 8), 0, cfg)
        lq, _ = forward_with_cache(q, toks, init_cache(cfg, 1, 8), 0, cfg)
        a, b = np.asarray(lf)[0], np.asarray(lq)[0]
        cos = float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b)))
        assert cos > 0.99, cos

    def test_moe_engine_accepts_qparams(self):
        from kubetorch_tpu.models.moe import MoeConfig, moe_init

        cfg = MoeConfig.tiny(dtype=jnp.float32, remat=False, attn_impl="xla")
        q = quantize_params(moe_init(jax.random.PRNGKey(1), cfg))
        assert is_quantized(q["layers"]["experts"]["w_gate"])
        assert not is_quantized(q["layers"]["router"])
        eng = GenerationEngine(q, cfg, slots=1, max_len=32,
                               prefill_buckets=(4,))
        h = eng.submit([5, 6, 7], max_new_tokens=4)
        while eng.step():
            pass
        got = h.result(timeout=0)
        assert len(got) == 4 and all(0 <= t < cfg.vocab_size for t in got)

    def test_moe_gather_dequant_is_exact(self):
        """The decode gather path (int8 gathered FIRST, then dequantized)
        must match running on offline-dequantized experts bit-for-bit —
        gather commutes with the per-channel scale."""
        from kubetorch_tpu.models.moe import MoeConfig, moe_init

        cfg = MoeConfig.tiny(dtype=jnp.float32, remat=False, attn_impl="xla")
        q = quantize_params(moe_init(jax.random.PRNGKey(1), cfg))
        dq = dequantize_params(q, cfg.dtype)
        prompt = [5, 6, 7]

        def run(p):
            eng = GenerationEngine(p, cfg, slots=1, max_len=32,
                                   prefill_buckets=(4,))
            h = eng.submit(prompt, max_new_tokens=6)
            while eng.step():
                pass
            return h.result(timeout=0)

        assert run(q) == run(dq)


class TestInitQuantized:
    """llama_init_quantized: the HBM-frugal direct-int8 init that makes
    7B-class single-chip serving possible (bf16 init + quantize would OOM
    a 16 GB chip before the int8 copy exists)."""

    def test_structure_matches_two_step_path(self):
        from kubetorch_tpu.models.llama import LlamaConfig, llama_init
        from kubetorch_tpu.models.quant import (llama_init_quantized,
                                                quantize_params)
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        qp = llama_init_quantized(jax.random.PRNGKey(0), cfg)
        ref = quantize_params(llama_init(jax.random.PRNGKey(0), cfg))
        assert (jax.tree_util.tree_structure(qp)
                == jax.tree_util.tree_structure(ref))
        # deterministic per (rng, cfg)
        qp2 = llama_init_quantized(jax.random.PRNGKey(0), cfg)
        for a, b in zip(jax.tree_util.tree_leaves(qp),
                        jax.tree_util.tree_leaves(qp2)):
            assert (np.asarray(a) == np.asarray(b)).all()

    def test_engine_matches_scanned_generate(self):
        from kubetorch_tpu.models.generate import generate
        from kubetorch_tpu.models.llama import LlamaConfig
        from kubetorch_tpu.models.quant import llama_init_quantized
        from kubetorch_tpu.serve import GenerationEngine
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        qp = llama_init_quantized(jax.random.PRNGKey(3), cfg)
        want = np.asarray(generate(qp, jnp.asarray([[5, 17, 42]], jnp.int32),
                                   cfg, max_new_tokens=6))[0, 3:].tolist()
        eng = GenerationEngine(qp, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,), decode_block=4)
        h = eng.submit([5, 17, 42], max_new_tokens=6)
        while eng.step():
            pass
        assert h.result(timeout=0) == want


class TestInt4:
    """Nibble-packed int4: half of int8's decode bytes again. Same
    placement contract as int8 (dequant location never changes tokens);
    group-wise scales bound the quantization step to amax/7 per group."""

    def test_pack_unpack_and_roundtrip_error(self):
        from kubetorch_tpu.models.quant import (_dequant_int4,
                                                _quantize_leaf_int4)
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
        leaf = _quantize_leaf_int4(w, group=16)
        assert leaf["__kt_q4__"].shape == (32, 32)       # packed pairs
        assert leaf["scale"].shape == (4, 32)            # 64/16 groups
        wd = _dequant_int4(leaf, jnp.float32)
        err = np.asarray(jnp.abs(wd - w))
        # per-group bound: half a quant step of that group's amax
        wg = np.asarray(w).reshape(4, 16, 32)
        bound = np.abs(wg).max(axis=1, keepdims=True) / 7 * 0.51 + 1e-6
        assert (err.reshape(4, 16, 32) <= bound).all()

    def test_generate_and_engine_match_dequantized_view(self):
        from kubetorch_tpu.models.quant import (dequantize_params,
                                                quantize_params_int4)
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        p4 = quantize_params_int4(llama_init(jax.random.PRNGKey(0), cfg),
                                  group=16)
        prompt = jnp.asarray([[5, 17, 42]], jnp.int32)
        want = np.asarray(generate(p4, prompt, cfg,
                                   max_new_tokens=6))[0, 3:].tolist()
        dq = dequantize_params(p4, jnp.float32)
        got = np.asarray(generate(dq, prompt, cfg,
                                  max_new_tokens=6))[0, 3:].tolist()
        assert got == want
        eng = GenerationEngine(p4, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,), decode_block=4)
        h = eng.submit([5, 17, 42], max_new_tokens=6)
        while eng.step():
            pass
        assert h.result(timeout=0) == want

    def test_direct_int4_init_matches_structure(self):
        from kubetorch_tpu.models.quant import (llama_init_quantized,
                                                quantize_params_int4,
                                                quantized_bytes)
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        p4i = llama_init_quantized(jax.random.PRNGKey(0), cfg, bits=4)
        ref = quantize_params_int4(llama_init(jax.random.PRNGKey(0), cfg))
        assert (jax.tree_util.tree_structure(p4i)
                == jax.tree_util.tree_structure(ref))
        b4 = quantized_bytes(p4i)["quantized"]
        from kubetorch_tpu.models.quant import llama_init_quantized as liq
        b8 = quantized_bytes(liq(jax.random.PRNGKey(0), cfg,
                                 bits=8))["quantized"]
        assert b4 < 0.75 * b8                      # packed, not just typed

    def test_moe_experts_stay_int8(self):
        from kubetorch_tpu.models.moe import MoeConfig, moe_init
        from kubetorch_tpu.models.quant import QKEY, quantize_params_int4
        cfg = MoeConfig.tiny(n_experts=4)
        p4 = quantize_params_int4(moe_init(jax.random.PRNGKey(0), cfg))
        experts = p4["layers"]["experts"]
        leaf = next(iter(v for v in experts.values()))
        assert QKEY in leaf                        # int8, gather-indexable
        assert "__kt_q4__" in p4["layers"]["wq"]


class TestQ4Kernel:
    """Fused int4 matmul (ops/quant_matmul.py): the packed nibbles are the
    HBM stream; unpack happens in VMEM. Interpret mode here; the on-chip
    path is exercised by scripts/tpu_big_serve.py."""

    def _leaf_and_x(self, din=256, dout=512, b=8):
        from kubetorch_tpu.models.quant import _quantize_leaf_int4
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (din, dout), jnp.float32)
        x = jax.random.normal(jax.random.fold_in(key, 1), (b, din),
                              jnp.float32)
        return x, w, _quantize_leaf_int4(w, group=128)

    def test_kernel_matches_xla_dequant(self):
        from kubetorch_tpu.models.quant import _dequant_int4
        from kubetorch_tpu.ops.quant_matmul import q4_matmul, q4_supported
        x, _w, leaf = self._leaf_and_x()
        assert q4_supported(x.shape, leaf["__kt_q4__"].shape,
                            leaf["scale"].shape)
        ref = (x.astype(jnp.bfloat16)
               @ _dequant_int4(leaf, jnp.bfloat16)).astype(jnp.float32)
        got = q4_matmul(x, leaf["__kt_q4__"], leaf["scale"])
        rel = (float(jnp.max(jnp.abs(got - ref)))
               / float(jnp.max(jnp.abs(ref))))
        assert rel < 0.02, rel

    def test_wdot_dispatches_and_fallback_agrees(self):
        from kubetorch_tpu.models.quant import _quantize_leaf_int4, wdot
        x, w, leaf = self._leaf_and_x()
        via_kernel = wdot(x.astype(jnp.bfloat16), leaf)
        # an untileable group (din 256 / group 64 → block_k 64) falls back
        leaf_small = _quantize_leaf_int4(w, group=64)
        via_fallback = wdot(x.astype(jnp.bfloat16), leaf_small)
        assert via_kernel.shape == via_fallback.shape == (8, 512)
        # both approximate the real product
        ref = (x @ w).astype(jnp.float32)
        for got in (via_kernel, via_fallback):
            rel = (float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref)))
                   / float(jnp.max(jnp.abs(ref))))
            assert rel < 0.2, rel          # 4-bit weights, loose bound

    def test_wdot_plain_array_is_plain_matmul(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (16, 8), jnp.float32)
        from kubetorch_tpu.models.quant import wdot
        assert (np.asarray(wdot(x, w)) == np.asarray(x @ w)).all()
