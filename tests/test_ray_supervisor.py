"""Ray distribution mode executed end-to-end (serving/ray_supervisor.py).

The supervisor's job is PROCESS MANAGEMENT: elect/honor the head, start the
GCS and wait for its port, join workers against it, run user code through a
single head-side ProcessWorker, refuse calls on workers, and tear the ray
processes down. All of that runs here against real pod-server subprocesses
(the LOCAL_IPS fake, as in test_distributed.py) and a minimal ``ray`` CLI
double (tests/assets/fake_ray/ray) that reproduces the contract the
supervisor drives: listener on the GCS port for ``start --head``,
connect-or-fail for ``start --address``, foreground ``--block`` semantics.
What it cannot prove: Ray's own scheduling inside user code — that needs
``ray`` in the image (reference CI runs real clusters; PARITY.md notes the
descope).
"""

import json
import os
import subprocess
import sys
import time

import pytest
import requests

from kubetorch_tpu.utils.procs import free_port, wait_for_port

pytestmark = [pytest.mark.level("minimal"), pytest.mark.slow]

ASSETS = os.path.join(os.path.dirname(__file__), "assets")
FAKE_RAY = os.path.join(ASSETS, "fake_ray")
GCS_PORT = 6379


def spawn_ray_pod(ip: str, port: int, ips: list, role: str = ""):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",          # never dial the TPU relay
        "PATH": FAKE_RAY + os.pathsep + env.get("PATH", ""),
        "LOCAL_IPS": ",".join(ips),
        "POD_IP": ip,
        "POD_NAME": f"pod-{ip.split('.')[-1]}",
        "KT_PROJECT_ROOT": ASSETS,
        "KT_MODULE_NAME": "payloads",
        "KT_FILE_PATH": "payloads.py",
        "KT_CLS_OR_FN_NAME": "whoami",
        "KT_LAUNCH_ID": "launch-ray",
        "KT_SERVICE_NAME": "ray-svc",
        "KT_DISTRIBUTED_CONFIG": json.dumps({
            "distribution_type": "ray", "workers": len(ips),
            "procs_per_worker": 1}),
        "KT_SERVER_PORT": str(port),
    })
    if role:
        env["KT_RAY_ROLE"] = role
    return subprocess.Popen(
        [sys.executable, "-m", "kubetorch_tpu.serving.http_server",
         "--host", ip, "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _teardown(procs):
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()


def _wait_ready(ip, port, proc, timeout=60):
    """Pod port up AND /health green (ray head setup is async work)."""
    assert wait_for_port(ip, port, timeout=timeout), _tail(proc)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            r = requests.get(f"http://{ip}:{port}/health", timeout=5)
            if r.status_code == 200:
                return
        except requests.ConnectionError:
            pass
        time.sleep(0.5)
    raise AssertionError(f"pod {ip} never became healthy: {_tail(proc)}")


def _tail(proc):
    proc.terminate()
    try:
        out = proc.communicate(timeout=5)[0]
    except subprocess.TimeoutExpired:
        proc.kill()
        out = ""
    return (out or "")[-2000:]


def test_ray_head_and_worker_lowest_ip_election():
    """Homogeneous pods (Deployment path): lowest IP becomes the head,
    starts the GCS, serves calls through its ProcessWorker; the worker
    joins the GCS and refuses user calls."""
    ips = ["127.0.0.2", "127.0.0.3"]
    port = free_port()
    procs = [spawn_ray_pod(ip, port, ips) for ip in ips]
    try:
        _wait_ready(ips[0], port, procs[0])
        # the head's GCS stand-in is live on the fixed ray port
        assert wait_for_port(ips[0], GCS_PORT, timeout=10)
        _wait_ready(ips[1], port, procs[1])

        # user code runs on the head only — one subprocess, not a fan-out
        r = requests.post(f"http://{ips[0]}:{port}/whoami",
                          json={"args": [], "kwargs": {}}, timeout=60)
        assert r.status_code == 200, r.text
        result = r.json()
        if isinstance(result, list):
            assert len(result) == 1
            result = result[0]
        # ExecutionSupervisor semantics on the head: a world of ONE pod
        assert result["pod_ips"] == ips[0]
        assert result["world_size"] == "1" and result["rank"] == "0"

        # the worker pod hosts ray processes only; calls are refused
        r = requests.post(f"http://{ips[1]}:{port}/whoami",
                          json={"args": [], "kwargs": {}}, timeout=60)
        assert r.status_code >= 400
        assert "head" in r.text.lower()
    finally:
        _teardown(procs)


def test_ray_kuberay_roles_and_gcs_probe():
    """KubeRay path (KT_RAY_ROLE): the designated head keeps the GCS even
    when it is NOT the lowest IP, and the worker finds it by probing the
    discovered set for the live GCS port (_find_gcs), not by rank."""
    head_ip, worker_ip = "127.0.0.5", "127.0.0.4"   # head deliberately higher
    ips = sorted([head_ip, worker_ip])
    port = free_port()
    head = spawn_ray_pod(head_ip, port, ips, role="head")
    worker = spawn_ray_pod(worker_ip, port, ips, role="worker")
    try:
        _wait_ready(head_ip, port, head)
        assert wait_for_port(head_ip, GCS_PORT, timeout=10)
        _wait_ready(worker_ip, port, worker)

        r = requests.post(f"http://{head_ip}:{port}/whoami",
                          json={"args": [], "kwargs": {}}, timeout=60)
        assert r.status_code == 200, r.text
        # the elected-by-IP candidate (lowest) must NOT have a GCS: role won
        assert not wait_for_port(worker_ip, GCS_PORT, timeout=1)
    finally:
        _teardown([head, worker])
