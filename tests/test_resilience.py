"""Unit coverage for the resilience layer (retry policies, circuit breaker,
deadline propagation, idempotency cache, chaos grammar) plus the data-plane
retry semantics against a live (threaded, in-process) store."""

import threading
import time

import pytest

pytestmark = pytest.mark.level("unit")

from kubetorch_tpu import chaos
from kubetorch_tpu import resilience as rz
from kubetorch_tpu.exceptions import CircuitOpenError, DeadlineExceededError


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_backoff_is_deterministic_and_recorded():
    policy = rz.RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=0.4,
                            seed=1234)
    record = []
    attempts = []

    def fn(info):
        attempts.append(info.index)
        if len(attempts) < 4:
            raise ConnectionError("transient")
        return "ok"

    out = policy.run(fn, retryable_exc=lambda e: True, record=record,
                     sleep=lambda s: None)
    assert out == "ok"
    assert attempts == [0, 1, 2, 3]
    assert record == policy.preview_delays(3)
    # full jitter stays within the exponential envelope
    for i, d in enumerate(record):
        assert 0.0 <= d <= min(0.4, 0.05 * 2 ** i)


def test_non_retryable_raises_immediately():
    calls = []

    def fn(info):
        calls.append(info.index)
        raise ValueError("terminal")

    with pytest.raises(ValueError):
        rz.RetryPolicy(max_attempts=5).run(
            fn, retryable_exc=lambda e: isinstance(e, ConnectionError),
            sleep=lambda s: None)
    assert calls == [0]


def test_attempts_exhausted_returns_last_response():
    """A still-failing response after the last attempt is returned as-is so
    the caller surfaces the real error, not a retry-layer one."""

    class Resp:
        status_code = 503
        headers = {}

    policy = rz.RetryPolicy(max_attempts=3, base_delay=0.001, seed=0)
    seen = []
    out = policy.run(lambda info: Resp(),
                     retryable_exc=lambda e: True,
                     response_retry_delay=lambda r: (seen.append(r) or True))
    assert isinstance(out, Resp)
    assert len(seen) == 3


def test_retry_after_floor_applies():
    policy = rz.RetryPolicy(max_attempts=2, base_delay=0.0001,
                            max_delay=0.001, seed=7)
    slept = []

    class Resp:
        headers = {"Retry-After": "0.25"}

    def verdict(resp):
        return rz.retry_after_seconds(resp)

    policy.run(lambda info: Resp(), retryable_exc=lambda e: False,
               response_retry_delay=lambda r: (
                   None if slept else verdict(r)),
               sleep=slept.append)
    assert slept and slept[0] >= 0.25


def test_deadline_stops_retries():
    policy = rz.RetryPolicy(max_attempts=50, base_delay=0.05, deadline=0.15,
                            seed=3)

    def fn(info):
        raise ConnectionError("down")

    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError) as ei:
        policy.run(fn, retryable_exc=lambda e: True)
    assert time.monotonic() - t0 < 2.0
    assert ei.value.deadline is not None


def test_deadline_clamps_attempt_timeout():
    policy = rz.RetryPolicy(max_attempts=1, attempt_timeout=60.0)
    seen = {}

    def fn(info):
        seen["timeout"] = info.timeout
        return 1

    policy.run(fn, retryable_exc=lambda e: False,
               deadline=rz.Deadline.after(0.5))
    assert seen["timeout"] <= 0.5


def test_deadline_header_roundtrip():
    d = rz.Deadline.after(5.0)
    back = rz.Deadline.from_header(d.header_value())
    assert back is not None and abs(back.at - d.at) < 1e-5
    assert rz.Deadline.from_header(None) is None
    assert rz.Deadline.from_header("garbage") is None
    assert not d.expired() and rz.Deadline(at=time.time() - 1).expired()


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


def test_circuit_opens_half_opens_and_closes():
    now = [0.0]
    br = rz.CircuitBreaker(failure_threshold=3, cooldown_s=10.0,
                           clock=lambda: now[0])

    def boom():
        raise RuntimeError("down")

    for _ in range(3):
        with pytest.raises(RuntimeError):
            br.call(boom)
    assert br.state == "open"
    with pytest.raises(CircuitOpenError) as ei:
        br.allow()
    assert 0 < ei.value.retry_after <= 10.0

    # cool-down elapses → half-open admits exactly one probe
    now[0] = 11.0
    br.allow()
    assert br.state == "half-open"
    with pytest.raises(CircuitOpenError):
        br.allow()          # second concurrent probe rejected
    br.record_failure()     # probe failed → open again, fresh cool-down
    assert br.state == "open"
    with pytest.raises(CircuitOpenError):
        br.allow()

    now[0] = 22.0
    br.allow()
    br.record_success()
    assert br.state == "closed"
    br.allow()              # closed circuit flows freely


def test_circuit_open_error_rehydrates():
    from kubetorch_tpu.exceptions import package_exception, rehydrate_exception

    out = rehydrate_exception(package_exception(
        CircuitOpenError("open", retry_after=4.5)))
    assert isinstance(out, CircuitOpenError) and out.retry_after == 4.5


# ---------------------------------------------------------------------------
# IdempotencyCache
# ---------------------------------------------------------------------------


def test_idempotency_cache_ttl_and_cap():
    now = [0.0]
    cache = rz.IdempotencyCache(ttl_s=10.0, max_entries=2,
                                clock=lambda: now[0])
    cache.store("a", 1)
    assert cache.lookup("a") == 1
    now[0] = 11.0
    assert cache.lookup("a") is None      # expired
    cache.store("b", 2)
    cache.store("c", 3)
    cache.store("d", 4)                   # evicts oldest beyond cap
    assert cache.lookup("b") is None
    assert cache.lookup("c") == 3 and cache.lookup("d") == 4
    assert len(cache) == 2


# ---------------------------------------------------------------------------
# Chaos grammar
# ---------------------------------------------------------------------------


def test_chaos_grammar_parses_all_forms():
    faults = chaos.parse_spec("reset*2, 503:0.2, delay:0.1@/kv, oom%0.5, pass")
    kinds = [f.kind for f in faults]
    assert kinds == ["reset", "reset", "status", "delay", "oom", "pass"]
    assert faults[2].status == 503 and faults[2].retry_after == 0.2
    assert faults[3].path == "/kv" and faults[3].seconds == 0.1
    assert faults[4].prob == 0.5


@pytest.mark.parametrize("bad", ["bogus", "delay:x", "503:x", "reset*x"])
def test_chaos_grammar_rejects_typos(bad):
    with pytest.raises(chaos.ChaosError):
        chaos.parse_spec(bad)


def test_chaos_schedule_consumes_in_order_and_respects_exemptions():
    engine = chaos.ChaosEngine(chaos.parse_spec("reset,pass,503"), seed=0)
    assert engine.next_fault("/health") is None       # exempt, not consumed
    assert engine.next_fault("/summer").kind == "reset"
    assert engine.next_fault("/summer") is None       # explicit pass token
    assert engine.next_fault("/summer").kind == "status"
    assert engine.next_fault("/summer") is None       # schedule exhausted
    assert engine.injected == 2


def test_chaos_probabilistic_is_seeded():
    def draws(seed):
        engine = chaos.ChaosEngine(chaos.parse_spec("503%0.5"), seed=seed)
        return [engine.next_fault("/x") is not None for _ in range(32)]

    assert draws(7) == draws(7)
    assert draws(7) != draws(8)           # astronomically unlikely to match
    assert any(draws(7)) and not all(draws(7))


# ---------------------------------------------------------------------------
# netpool.request semantics against a live (in-process) server
# ---------------------------------------------------------------------------


def _flaky_app(calls, fail=2, status=503, retry_after=None):
    from aiohttp import web

    async def handler(request):
        calls.append(time.monotonic())
        if len(calls) <= fail:
            headers = {}
            if retry_after is not None:
                headers["Retry-After"] = str(retry_after)
            return web.Response(status=status, headers=headers, text="busy")
        return web.json_response({"ok": True})

    app = web.Application()
    app.router.add_get("/thing", handler)
    return app


def test_store_retries_honor_retry_after():
    from kubetorch_tpu.data_store import netpool
    from tests.assets.threaded_server import ThreadedAiohttpServer

    calls = []
    with ThreadedAiohttpServer(
            lambda: _flaky_app(calls, fail=2, retry_after=0.35)) as srv:
        policy = rz.RetryPolicy(max_attempts=4, base_delay=0.001,
                                max_delay=0.01, seed=5)
        record = []
        r = netpool.request("GET", f"{srv.url}/thing", policy=policy,
                            record=record)
    assert r.status_code == 200 and len(calls) == 3
    # the Retry-After floor (0.35s) overrode the tiny policy backoff
    assert all(d >= 0.35 for d in record)
    assert all(b - a >= 0.3 for a, b in zip(calls, calls[1:]))


def test_store_gives_up_after_max_attempts():
    from kubetorch_tpu.data_store import netpool
    from tests.assets.threaded_server import ThreadedAiohttpServer

    calls = []
    with ThreadedAiohttpServer(
            lambda: _flaky_app(calls, fail=99)) as srv:
        policy = rz.RetryPolicy(max_attempts=3, base_delay=0.001,
                                max_delay=0.01, seed=5)
        r = netpool.request("GET", f"{srv.url}/thing", policy=policy)
    assert r.status_code == 503 and len(calls) == 3


def test_store_does_not_retry_definitive_statuses():
    from kubetorch_tpu.data_store import netpool
    from tests.assets.threaded_server import ThreadedAiohttpServer

    calls = []
    with ThreadedAiohttpServer(
            lambda: _flaky_app(calls, fail=99, status=404)) as srv:
        r = netpool.request("GET", f"{srv.url}/thing",
                            policy=rz.RetryPolicy(max_attempts=5,
                                                  base_delay=0.001))
    assert r.status_code == 404 and len(calls) == 1


def test_store_breaker_opt_in(monkeypatch):
    """KT_STORE_BREAKER_THRESHOLD>0 trips the per-netloc breaker after
    consecutive failures and half-opens after the cool-down."""
    from kubetorch_tpu.data_store import netpool
    from tests.assets.threaded_server import ThreadedAiohttpServer

    monkeypatch.setenv("KT_STORE_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("KT_STORE_BREAKER_COOLDOWN_S", "0.2")
    netpool.reset_breakers()
    calls = []
    try:
        with ThreadedAiohttpServer(
                lambda: _flaky_app(calls, fail=2)) as srv:
            policy = rz.RetryPolicy(max_attempts=1)
            for _ in range(2):
                netpool.request("GET", f"{srv.url}/thing", policy=policy)
            with pytest.raises(CircuitOpenError):
                netpool.request("GET", f"{srv.url}/thing", policy=policy)
            assert len(calls) == 2        # third call never hit the wire
            time.sleep(0.25)              # cool-down → half-open probe
            r = netpool.request("GET", f"{srv.url}/thing", policy=policy)
            assert r.status_code == 200
            assert netpool.request("GET", f"{srv.url}/thing",
                                   policy=policy).status_code == 200
    finally:
        netpool.reset_breakers()


def test_half_open_admits_single_probe_across_threads():
    br = rz.CircuitBreaker(failure_threshold=1, cooldown_s=0.05)
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError()))
    time.sleep(0.06)
    admitted, rejected = [], []
    barrier = threading.Barrier(4)

    def probe():
        barrier.wait()
        try:
            br.allow()
            admitted.append(1)
        except CircuitOpenError:
            rejected.append(1)

    threads = [threading.Thread(target=probe) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(admitted) == 1 and len(rejected) == 3
