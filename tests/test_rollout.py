"""Live weight rollout suite (ISSUE 11): broadcast-tree routing protocol,
delta fetch + fingerprint-gated hot swap + rollback, canary pinning with
auto-rollback, the kill-peer chaos verb, and the mid-broadcast SIGKILL
acceptance drill. ``make test-rollout``."""

import json
import os
import threading
import time

import numpy as np
import pytest
import requests

from kubetorch_tpu import telemetry
from kubetorch_tpu.chaos import ChaosEngine, ChaosError, parse_spec
from kubetorch_tpu.data_store import commands as ds
from kubetorch_tpu.data_store import ring as ring_mod
from kubetorch_tpu.exceptions import RolloutError
from kubetorch_tpu.serve import rollout as ro
from kubetorch_tpu.train import checkpoint as ck
from tests.assets.threaded_server import ThreadedAiohttpServer


@pytest.fixture
def store(tmp_path, monkeypatch):
    monkeypatch.setenv("KT_STORE_FSYNC", "0")
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    from kubetorch_tpu.data_store.store_server import create_store_app
    ring_mod.reset_rings()
    with ThreadedAiohttpServer(
            lambda: create_store_app(str(tmp_path / "store"))) as srv:
        yield srv.url
    ring_mod.reset_rings()


def _route(url, key, self_url):
    return requests.post(f"{url}/route", json={
        "key": key, "self_url": self_url}, timeout=10).json()


def _fail(url, key, victim):
    return requests.post(f"{url}/route/failed", json={
        "key": key, "url": victim}, timeout=10).json()


def _tree():
    return {"layers": {"w1": np.arange(64, dtype=np.float32).reshape(8, 8),
                       "w2": np.ones((4, 4), np.float32)},
            "norm": np.full((8,), 2.0, np.float32)}


def _zeros_like_tree():
    return {"layers": {"w1": np.zeros((8, 8), np.float32),
                       "w2": np.zeros((4, 4), np.float32)},
            "norm": np.zeros((8,), np.float32)}


# ---------------------------------------------------------------------------
# broadcast-tree routing protocol
# ---------------------------------------------------------------------------


def test_route_depth_aware_breadth_first(store, monkeypatch):
    """With fanout 2: the tree fills breadth-first (shallowest free parent
    wins) and no member ever exceeds its out-degree."""
    monkeypatch.setenv("KT_ROUTE_FANOUT", "2")
    key = "bt/k"
    r = _route(store, key, "http://a")
    assert (r["source"], r["depth"]) == ("store", 1)
    assert (_route(store, key, "http://b")["url"],
            _route(store, key, "http://c")["url"]) == ("http://a",) * 2
    # A is full (fanout 2): D lands at depth 3 under B or C
    r = _route(store, key, "http://d")
    assert r["url"] in ("http://b", "http://c") and r["depth"] == 3
    # E prefers the other depth-2 member (fewest children tie-break)
    r2 = _route(store, key, "http://e")
    assert r2["url"] in ("http://b", "http://c") and r2["url"] != r["url"]


def test_route_failed_frees_slot_and_orphans_children(store, monkeypatch):
    monkeypatch.setenv("KT_ROUTE_FANOUT", "2")
    key = "bt/fail"
    _route(store, key, "http://a")                  # root
    assert _route(store, key, "http://b")["url"] == "http://a"
    assert _route(store, key, "http://c")["url"] == "http://a"
    assert _route(store, key, "http://d")["url"] in ("http://b", "http://c")
    parent_of_d = "http://b"
    out = _fail(store, key, parent_of_d)
    assert out["evicted"] is True
    # D was orphaned iff its parent was B; either way the eviction frees
    # A's child slot, so the next joiner lands back at depth 2
    r = _route(store, key, "http://e")
    assert r["url"] != parent_of_d
    assert r["depth"] == 2


def test_route_reroute_replaces_edge_not_double_books(store, monkeypatch):
    monkeypatch.setenv("KT_ROUTE_FANOUT", "2")
    key = "bt/rebook"
    _route(store, key, "http://a")
    for _ in range(3):                  # B re-asks: edge replaced, not added
        assert _route(store, key, "http://b")["url"] == "http://a"
    # A must still have exactly one slot free (B counts once)
    assert _route(store, key, "http://c")["url"] == "http://a"
    assert _route(store, key, "http://d")["depth"] == 3


def test_route_never_assigns_own_descendant(store, monkeypatch):
    """A re-routing member must not be handed its own child (cycle)."""
    monkeypatch.setenv("KT_ROUTE_FANOUT", "1")
    key = "bt/cycle"
    _route(store, key, "http://a")                      # root, depth 1
    assert _route(store, key, "http://b")["url"] == "http://a"
    assert _route(store, key, "http://c")["url"] == "http://b"
    _fail(store, key, "http://a")                       # B orphaned
    r = _route(store, key, "http://b")
    # the only registered member with a free slot is C — B's descendant:
    # must be refused, B roots at the store instead
    assert r["source"] == "store"


def test_fetcher_reparents_after_dead_peer(store, monkeypatch):
    """A dead parent triggers /route/failed AND a fresh /route resolution
    (client-side re-parenting) before the origin covers the fetch."""
    monkeypatch.setenv("KT_ROUTE_RETRIES", "2")
    key = "bt/reparent"
    ds.put(key, np.arange(16, dtype=np.int32), store_url=store)
    # a dead peer is registered as the sole broadcast parent
    _route(store, key, "http://127.0.0.1:9")
    fetcher = ds._RoutedFetcher(store, key, peer=True)
    r = fetcher.fetch(f"{key}{ds._INDEX_SUFFIX}")
    assert r.status_code == 200
    assert fetcher._reroutes == 1          # evict → re-route → store root
    assert fetcher.bytes_by_source.get("store", 0) > 0
    # the dead parent was evicted server-side
    group = requests.post(f"{store}/route", json={
        "key": key, "self_url": None}, timeout=10).json()
    assert group.get("url") != "http://127.0.0.1:9"


def test_content_alias_skips_stale_cache(store, tmp_path, monkeypatch):
    """content_alias=True keys the peer cache by subkey@hash: a stale
    bare-key (or old-hash) entry is a clean miss, and the fresh bytes are
    re-cached under the aliased key for later joiners."""
    monkeypatch.setenv("POD_IP", "127.0.0.1")
    monkeypatch.setenv("KT_SERVER_PORT", "1")
    monkeypatch.setenv("KT_DATA_CACHE_DIR", str(tmp_path / "cache"))
    from kubetorch_tpu.data_store import peer_cache

    key = "bt/alias"
    new = np.arange(8, dtype=np.int32)
    ds.put(key, new, store_url=store)      # pytree: leaf at {key}/value
    subkey = f"{key}/value"
    want = ds._leaf_hash(new)
    # poison the bare-key cache with stale bytes (the pre-alias hazard)
    stale = np.zeros(8, np.int32)
    peer_cache.cache_put(subkey, stale.tobytes(),
                         {"dtype": "int32", "shape": [8], "kind": "array"})
    fetcher = ds._RoutedFetcher(store, key, peer=True, content_alias=True)
    r = fetcher.fetch(subkey, expect_hash=want)
    assert r.status_code == 200
    got = np.frombuffer(r.content, dtype=np.int32)
    np.testing.assert_array_equal(got, new)
    assert peer_cache.cache_get(f"{subkey}@{want[:12]}") is not None


# ---------------------------------------------------------------------------
# fingerprints + manifests
# ---------------------------------------------------------------------------


def test_tree_fingerprint_composes_from_leaf_hashes():
    tree = _tree()
    leaves = {}
    ds._flatten(tree, "", leaves)
    hashes = {p: ds._leaf_hash(np.ascontiguousarray(np.asarray(a)))
              for p, a in leaves.items()}
    assert ck.tree_fingerprint(tree) == ds.tree_fingerprint_of_hashes(hashes)


def test_publish_rollout_manifest_quorum_roundtrip(store):
    out = ck.publish_rollout("svc", _tree(), step=7, store_url=store)
    assert out["leaves"] == 3 and out["manifest"]["version"] == 1
    assert out["manifest"]["index_blake2b"]
    m = ro.read_manifest("svc", store_url=store)
    assert m["version"] == 1 and m["phase"] == "fleet"
    assert m["fingerprint"] == out["fingerprint"]
    # versions auto-increment; identical re-push moves no leaf bytes
    out2 = ck.publish_rollout("svc", _tree(), step=8, store_url=store)
    assert out2["manifest"]["version"] == 2
    assert out2["skipped"] == 3 and out2["bytes"] == 0


def test_publish_manifest_rejects_unknown_phase(store):
    with pytest.raises(ValueError):
        ro.publish_manifest("svc", key="k", phase="yolo", store_url=store)


# ---------------------------------------------------------------------------
# WeightRollout: apply / delta / gate / rollback / canary scoping
# ---------------------------------------------------------------------------


@pytest.fixture
def engine():
    eng = ro.HostEngine(_zeros_like_tree(), step_s=0.0005).start()
    yield eng
    eng.stop()


def test_apply_and_delta_swap(store, engine):
    tree1 = _tree()
    out1 = ck.publish_rollout("svc", tree1, step=1, store_url=store)
    wr = ro.WeightRollout(engine, "svc", store_url=store, replica_id="r1",
                          peer=False)
    req = engine.submit(50)            # decode stream across the swap
    res = wr.poll_once()
    assert res["version"] == 1 and res["leaves_changed"] == 3
    assert wr.fingerprint == out1["fingerprint"]
    np.testing.assert_array_equal(engine.params["layers"]["w1"],
                                  tree1["layers"]["w1"])
    assert req["done"].wait(10) and req["error"] is None
    # delta push: only the changed leaf moves/swaps
    tree2 = _tree()
    tree2["layers"]["w2"] = np.full((4, 4), 5.0, np.float32)
    ck.publish_rollout("svc", tree2, step=2, store_url=store)
    res2 = wr.poll_once()
    assert res2["leaves_changed"] == 1
    np.testing.assert_array_equal(engine.params["layers"]["w2"],
                                  tree2["layers"]["w2"])
    assert wr.poll_once() is None      # already converged


def test_fingerprint_gate_refuses_before_touching_engine(store, engine):
    ds.put(ro.weights_key("svc"), _tree(), store_url=store)
    ro.publish_manifest("svc", key=ro.weights_key("svc"),
                        fingerprint="deadbeef" * 5, store_url=store)
    wr = ro.WeightRollout(engine, "svc", store_url=store, peer=False)
    with pytest.raises(RolloutError) as ei:
        wr.poll_once()
    assert ei.value.reason == "fingerprint_mismatch"
    assert wr.version == 0 and wr.swaps == 0
    np.testing.assert_array_equal(engine.params["layers"]["w1"],
                                  np.zeros((8, 8), np.float32))
    assert wr.status()["last_error"]


def test_structure_change_is_typed_refusal(store, engine):
    bad = {"layers": {"w1": np.ones((8, 8), np.float32)}}   # missing leaves
    ck.publish_rollout("svc", bad, step=1, store_url=store)
    wr = ro.WeightRollout(engine, "svc", store_url=store, peer=False)
    with pytest.raises(RolloutError) as ei:
        wr.poll_once()
    assert ei.value.reason == "structure_mismatch"
    assert wr.swaps == 0


def test_shape_change_is_typed_refusal(store, engine):
    bad = _zeros_like_tree()
    bad["layers"]["w2"] = np.ones((2, 2), np.float32)       # wrong shape
    ck.publish_rollout("svc", bad, step=1, store_url=store)
    wr = ro.WeightRollout(engine, "svc", store_url=store, peer=False)
    with pytest.raises(RolloutError) as ei:
        wr.poll_once()
    assert ei.value.reason == "shape_mismatch"
    assert wr.swaps == 0


def test_canary_scoping_and_rollback(store):
    """Canary manifests swap ONLY the named replica; the rollback manifest
    rolls the canary back from its pre-swap stash and bumps everyone
    else's version without touching their weights."""
    eng1 = ro.HostEngine(_zeros_like_tree(), step_s=0.0).start()
    eng2 = ro.HostEngine(_zeros_like_tree(), step_s=0.0).start()
    try:
        wr1 = ro.WeightRollout(eng1, "svc", store_url=store,
                               replica_id="r1", peer=False)
        wr2 = ro.WeightRollout(eng2, "svc", store_url=store,
                               replica_id="r2", peer=False)
        tree1 = _tree()
        out1 = ck.publish_rollout("svc", tree1, step=1, store_url=store)
        assert wr1.poll_once()["version"] == 1
        assert wr2.poll_once()["version"] == 1
        # v2 canary-first: only r1 swaps
        tree2 = _tree()
        tree2["norm"] = np.full((8,), 9.0, np.float32)
        out2 = ck.publish_rollout("svc", tree2, step=2, store_url=store,
                                  phase="canary", canary="r1")
        assert wr1.poll_once()["version"] == 2
        assert wr2.poll_once() is None          # non-canary never swaps
        assert wr2.fingerprint == out1["fingerprint"]
        before = telemetry.REGISTRY.counter(
            "kt_rollout_rollbacks_total",
            labels=("reason",)).value(reason="canary_regression")
        # canary regressed: typed rollback toward the v1 fingerprint
        ro.publish_manifest("svc", key=out2["manifest"]["key"], step=1,
                            fingerprint=out1["fingerprint"],
                            phase="rollback", reason="canary_regression",
                            store_url=store)
        res = wr1.poll_once()
        assert res["rolled_back"] is True
        np.testing.assert_array_equal(eng1.params["norm"], tree1["norm"])
        assert wr1.fingerprint == out1["fingerprint"]
        res2 = wr2.poll_once()
        assert res2["rolled_back"] is False and wr2.swaps == 1
        assert wr1.version == wr2.version == 3
        after = telemetry.REGISTRY.counter(
            "kt_rollout_rollbacks_total",
            labels=("reason",)).value(reason="canary_regression")
        assert after == before + 1
        assert any(s["replica"] == "r1" for s in ro.local_status())
    finally:
        eng1.stop()
        eng2.stop()


def test_trainer_killed_before_manifest_leaves_fleet_on_old_version(
        store, engine):
    """The manifest PUT is the commit point: weights pushed without a
    manifest (trainer SIGKILLed mid-publish) change NOTHING fleet-side."""
    out1 = ck.publish_rollout("svc", _tree(), step=1, store_url=store)
    wr = ro.WeightRollout(engine, "svc", store_url=store, peer=False)
    wr.poll_once()
    # "trainer dies" after the weight push, before publish_manifest
    torn = _tree()
    torn["layers"]["w1"] = np.full((8, 8), 123.0, np.float32)
    ds.put(ro.weights_key("svc"), torn, store_url=store)
    assert wr.poll_once() is None
    assert wr.version == 1 and wr.fingerprint == out1["fingerprint"]


# ---------------------------------------------------------------------------
# GenerationEngine batch-boundary hook (the real engine's swap site)
# ---------------------------------------------------------------------------


def test_generation_engine_at_batch_boundary_runs_on_step_thread():
    import jax.numpy as jnp

    from kubetorch_tpu.models.llama import LlamaConfig, llama_init
    from kubetorch_tpu.serve.engine import GenerationEngine
    import jax

    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    eng = GenerationEngine(params, cfg, slots=2, max_len=64)
    # no loop thread: runs inline on the caller
    assert eng.at_batch_boundary(lambda: threading.get_ident()) \
        == threading.get_ident()
    eng.start()
    try:
        h = eng.submit([1, 2, 3], max_new_tokens=8)
        seen = {}

        def hook():
            seen["thread"] = threading.current_thread().name
            return 42

        assert eng.at_batch_boundary(hook, timeout=60) == 42
        assert seen["thread"] == "kt-gen-engine"
        assert len(h.result(timeout=60)) == 8
        # an erroring hook propagates to the CALLER, loop survives
        with pytest.raises(RuntimeError, match="boom"):
            eng.at_batch_boundary(
                lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                timeout=60)
        h2 = eng.submit([4, 5], max_new_tokens=4)
        assert len(h2.result(timeout=60)) == 4
    finally:
        eng.stop()


def test_weight_rollout_swaps_live_generation_engine(store):
    """The production path end to end: a REAL GenerationEngine decoding on
    its loop thread hot-swaps a trainer-published delta between batches —
    streams keep decoding, the fingerprint matches the trainer's, and the
    swapped leaf is live on device."""
    import jax
    import jax.numpy as jnp

    from kubetorch_tpu.models.llama import LlamaConfig, llama_init
    from kubetorch_tpu.serve.engine import GenerationEngine

    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    eng = GenerationEngine(params, cfg, slots=2, max_len=64)
    eng.start()
    try:
        h = eng.submit([1, 2, 3], max_new_tokens=16)
        # trainer: same tree with one leaf perturbed, pushed + published
        host = jax.tree_util.tree_map(
            lambda x: np.array(np.asarray(x), copy=True), params)
        host["final_norm"] = host["final_norm"] * 1.5
        out = ck.publish_rollout("llm", host, step=1, store_url=store)
        wr = ro.WeightRollout(eng, "llm", store_url=store, peer=False)
        res = wr.poll_once()
        assert res["version"] == 1 and res["leaves_changed"] == 1
        assert wr.fingerprint == out["fingerprint"]
        np.testing.assert_allclose(np.asarray(eng.params["final_norm"]),
                                   host["final_norm"], rtol=1e-6)
        # the in-flight stream survived the swap
        assert len(h.result(timeout=120)) == 16
        h2 = eng.submit([4, 5], max_new_tokens=4)
        assert len(h2.result(timeout=120)) == 4
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# chaos: kill-peer parse + scoping
# ---------------------------------------------------------------------------


def test_kill_peer_parse():
    import signal

    f = parse_spec("kill-peer@2")[0]
    assert (f.kind, f.op_index, f.signal_no) == ("kill-peer", 2, 9)
    f = parse_spec("kill-peer:TERM@1")[0]
    assert (f.kind, f.op_index, f.signal_no) == (
        "kill-peer", 1, int(signal.SIGTERM))
    with pytest.raises(ChaosError):
        parse_spec("kill-peer@notanumber")


def test_kill_peer_counts_only_broadcast_transfers():
    """Method-aware scoping: only client-origin GET/HEAD on the transfer
    surface advance the kill-peer op counter — PUTs, control POSTs,
    probe routes, and internal traffic never do."""
    eng = ChaosEngine(parse_spec("kill-peer@1"))
    assert eng.next_fault("/kv/diff", "POST") is None       # control POST
    assert eng.next_fault("/kv/a", "PUT") is None           # write
    assert eng.next_fault("/health", "GET") is None         # probe
    assert eng.next_fault("/route", "POST") is None         # coordinator
    assert eng.peer_ops == 0
    assert eng.next_fault("/_kt/data/x", "GET") is None     # transfer #0
    assert eng.peer_ops == 1
    internal = eng.next_fault("/kv/b", "GET", internal=True)
    assert internal is None and eng.peer_ops == 1           # internal exempt
    fault = eng.next_fault("/blob/abc", "GET")              # transfer #1
    assert fault is not None and fault.kind == "kill-peer"


def test_kill_peer_and_kill_store_node_schedules_are_independent():
    eng = ChaosEngine(parse_spec("kill-peer@0,kill-store-node@1"))
    # a PUT is data-op #0 (node schedule) but NOT a peer transfer
    assert eng.next_fault("/kv/a", "PUT") is None
    # the first GET transfer fires kill-peer (peer op #0) even though it
    # would also have been data-op #1 for the node schedule
    fault = eng.next_fault("/kv/a", "GET")
    assert fault is not None and fault.kind == "kill-peer"


# ---------------------------------------------------------------------------
# router canary pinning + verdict
# ---------------------------------------------------------------------------


@pytest.mark.serve
class TestRouterCanary:
    IPS = ["10.1.0.1", "10.1.0.2", "10.1.0.3"]

    def _dispatch(self, router, pool, n=1):
        import asyncio

        async def go():
            out = []
            for _ in range(n):
                out.append(await router.dispatch(
                    pool=pool, ips=self.IPS, my_ip="9.9.9.9", method=None,
                    args=[], kwargs={}, headers=None, timeout=None,
                    local_call=None))
            return out
        return asyncio.run(go())

    def _pool(self):
        from tests.test_serve_router import FakePool
        return FakePool()

    def test_full_slice_pins_canary_first(self):
        from kubetorch_tpu.serving.router import Router
        router = Router(fn_name="f")
        pool = self._pool()
        router.set_canary("10.1.0.2", fraction=1.0)
        self._dispatch(router, pool, n=6)
        assert set(pool.calls) == {"10.1.0.2"}
        st = router.canary_state()
        assert st["requests"] == 6 and st["errors"] == 0
        assert router.canary_verdict(min_requests=6) == "ok"

    def test_fractional_slice_and_avoidance(self):
        from kubetorch_tpu.serving.router import Router
        router = Router(fn_name="f")
        pool = self._pool()
        router.set_canary("10.1.0.2", fraction=0.25)
        self._dispatch(router, pool, n=8)
        canary_hits = sum(1 for ip in pool.calls if ip == "10.1.0.2")
        assert canary_hits == 2            # exactly the slice
        router.clear_canary()
        assert router.canary_state() is None

    def test_error_rate_regression(self):
        from kubetorch_tpu.serving.router import Router
        router = Router(fn_name="f")
        pool = self._pool()
        pool.app_error.add("10.1.0.2")
        router.set_canary("10.1.0.2", fraction=1.0)
        for _ in range(5):
            with pytest.raises(ValueError):
                self._dispatch(router, pool, n=1)
        assert router.canary_verdict(min_requests=5,
                                     err_threshold=0.05) == "regressed"

    def test_latency_regression_vs_preswap_ewma(self):
        import asyncio

        from kubetorch_tpu.serving.router import Router
        from tests.test_serve_router import FakePool

        class SlowPool(FakePool):
            async def call_worker(self, ip, *a, **kw):
                if ip == "10.1.0.2":
                    await asyncio.sleep(0.05)
                return await super().call_worker(ip, *a, **kw)

        router = Router(fn_name="f")
        router._ewma_s = 0.001             # the pre-swap baseline
        pool = SlowPool()
        router.set_canary("10.1.0.2", fraction=1.0)
        self._dispatch(router, pool, n=4)
        assert router.canary_verdict(min_requests=3,
                                     ttft_factor=2.0) == "regressed"
        assert router.state_dict()["canary"]["lat_ewma_s"] > 0.01

    def test_warming_until_min_requests(self):
        from kubetorch_tpu.serving.router import Router
        router = Router(fn_name="f")
        router.set_canary("10.1.0.2", fraction=1.0)
        assert router.canary_verdict(min_requests=5) == "warming"
        assert router.canary_verdict() != "regressed"


def test_canary_rollout_controller_promote_and_rollback(store):
    """CanaryRollout drives publish→bake→promote (clean) or
    publish→bake→typed rollback manifest (regressed verdict)."""

    class ScriptedRouter:
        def __init__(self, verdict):
            self.verdict = verdict
            self.pinned = None

        def set_canary(self, replica, fraction=0.1):
            self.pinned = (replica, fraction)

        def clear_canary(self):
            self.pinned = None

        def canary_verdict(self, **kw):
            return self.verdict

    calls = []

    def publish(phase, canary=None):
        calls.append(phase)
        return ck.publish_rollout("svc", _tree(), step=len(calls),
                                  store_url=store, phase=phase,
                                  canary=canary)["manifest"]

    # first-ever rollout: no baseline to regress from → straight to fleet
    ctl = ro.CanaryRollout("svc", ScriptedRouter("ok"), store_url=store,
                           bake_s=0.3, min_requests=1)
    assert ctl.run(publish, "r1") == "promoted"
    assert calls == ["fleet"]
    # clean bake: canary then fleet
    assert ctl.run(publish, "r1") == "promoted"
    assert calls == ["fleet", "canary", "fleet"]
    assert ro.read_manifest("svc", store_url=store)["phase"] == "fleet"
    # regression: canary then a typed rollback manifest to the PREVIOUS
    # fingerprint, never a fleet promote
    prev = ro.read_manifest("svc", store_url=store)
    ctl_bad = ro.CanaryRollout("svc", ScriptedRouter("regressed"),
                               store_url=store, bake_s=2.0, min_requests=1)
    assert ctl_bad.run(publish, "r1") == "rolled_back"
    assert calls == ["fleet", "canary", "fleet", "canary"]
    m = ro.read_manifest("svc", store_url=store)
    assert m["phase"] == "rollback"
    assert m["reason"] == "canary_regression"
    assert m["fingerprint"] == prev["fingerprint"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_rollout_status_json(store):
    from click.testing import CliRunner

    from kubetorch_tpu.cli import cli

    ck.publish_rollout("svc", _tree(), step=3, store_url=store)
    r = CliRunner().invoke(cli, ["rollout", "status", "--service", "svc",
                                 "--store-url", store, "--json"])
    assert r.exit_code == 0, r.output
    payload = json.loads(r.output)
    assert payload["manifest"]["version"] == 1
    assert payload["manifest"]["phase"] == "fleet"
    # human rendering too
    r = CliRunner().invoke(cli, ["rollout", "status", "--service", "svc",
                                 "--store-url", store])
    assert r.exit_code == 0, r.output
    assert "manifest: v1" in r.output


def test_cli_rollout_status_no_manifest(store):
    from click.testing import CliRunner

    from kubetorch_tpu.cli import cli

    r = CliRunner().invoke(cli, ["rollout", "status", "--service", "ghost",
                                 "--store-url", store])
    assert r.exit_code == 0, r.output
    assert "no rollout manifest" in r.output


# ---------------------------------------------------------------------------
# chaos acceptance: SIGKILL an interior peer + the trainer mid-broadcast
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_interior_peer_and_trainer_mid_broadcast(tmp_path):
    """The ISSUE-11 acceptance drill on a real subprocess fleet.

    Deterministic setup: the VICTIM replica converges to v1 alone first,
    so when the two survivors join they are both routed to it (the sole
    completed broadcast parent). It is armed with ``kill-peer@0`` — it
    SIGKILLs itself serving its FIRST transfer, i.e. mid-broadcast as an
    interior tree parent. The survivors must report ``/route/failed``,
    re-parent, and converge to the one v1 fingerprint with zero failed
    ``/generate`` calls. Then the trainer 'dies' after pushing v2 bytes
    but before the manifest commit — the fleet must stay on v1, never
    mixed-version — and a real v2 publish converges everyone."""
    import importlib.util

    from kubetorch_tpu.utils.procs import kill_process_tree

    spec = importlib.util.spec_from_file_location(
        "bench_rollout", os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "bench_rollout.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Args:
        leaves, leaf_kb, step_ms = 6, 8, 0.5

    rng = np.random.default_rng(1)
    elems = Args.leaf_kb * 256
    service = "chaos-accept"
    procs = []
    ring_mod.reset_rings()
    try:
        store_proc, store_url = bench._spawn_store(str(tmp_path / "store"))
        procs.append(store_proc)
        # the victim first, armed: kill-peer@0 = die on the 1st served
        # broadcast transfer (its own outbound fetch doesn't count — the
        # schedule is method/path-scoped to incoming GET transfers)
        os.environ["KT_CHAOS"] = "kill-peer@0"
        victim_proc, victim_url = bench._spawn_replica(
            0, str(tmp_path), store_url, service, True, Args)
        os.environ.pop("KT_CHAOS", None)
        procs.append(victim_proc)
        bench._wait_all_healthy([victim_url])

        tree = {"layers": {f"l{i}": rng.standard_normal(elems).astype(
            np.float32) for i in range(Args.leaves)}}
        out1 = ck.publish_rollout(service, tree, step=1,
                                  store_url=store_url)
        # victim converges alone → registers as the completed parent
        bench._wait_converged([victim_url], 1, out1["fingerprint"],
                              timeout=60)

        survivors = []
        for i in (1, 2):
            p, u = bench._spawn_replica(i, str(tmp_path), store_url,
                                        service, True, Args)
            procs.append(p)
            survivors.append(u)
        bench._wait_all_healthy(survivors)
        load = bench._OpenLoopLoad(survivors, qps=20).start()
        try:
            # the survivors' first fetch routes to the victim, whose first
            # served transfer kills it — the tree must re-parent
            bench._wait_converged(survivors, 1, out1["fingerprint"],
                                  timeout=90)
        finally:
            load.stop()
        assert load.dropped == 0, f"{load.dropped}/{load.sent} dropped"
        # the kill provably fired: the interior parent is DEAD
        assert victim_proc.poll() is not None, \
            "victim replica survived — the drill was vacuous"
        # re-parenting visible in the byte accounting: the survivors
        # covered the delta from the origin after losing their parent
        st = bench._fleet_status(survivors)
        assert sum(r.get("bytes", {}).get("origin", 0)
                   for r in st.values()) > 0

        # trainer SIGKILLed mid-publish: v2 bytes land, the manifest (the
        # commit point) never does — fleet must stay converged on v1
        torn = {"layers": dict(tree["layers"])}
        torn["layers"]["l0"] = rng.standard_normal(elems).astype(np.float32)
        ds.put(ro.weights_key(service), torn, store_url=store_url)
        time.sleep(1.0)
        st = bench._fleet_status(survivors)
        assert all(r.get("version") == 1
                   and r.get("fingerprint") == out1["fingerprint"]
                   for r in st.values()), st

        # a real publish of the same delta now converges everyone to ONE
        # fingerprint — never silently mixed
        out2 = ck.publish_rollout(service, torn, step=2,
                                  store_url=store_url)
        bench._wait_converged(survivors, 2, out2["fingerprint"], timeout=90)
    finally:
        os.environ.pop("KT_CHAOS", None)
        for p in procs:
            kill_process_tree(p.pid)
        ring_mod.reset_rings()
