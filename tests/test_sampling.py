"""Nucleus (top-p) sampling and stop sequences (serve/engine.py, generate).

The correctness lever for top-p: as top_p → 0 the nucleus shrinks to the
top-1 token, so a sampled run (any temperature) must reproduce the greedy
run exactly — that pins the sort/cumsum/scatter mask with no statistical
slack. Stop sequences: the stream must end exactly at the first suffix
match, mirroring ``eos_id`` semantics (matching tokens emitted).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubetorch_tpu.models.generate import generate, nucleus_mask
from kubetorch_tpu.models.llama import LlamaConfig, llama_init
from kubetorch_tpu.serve import GenerationEngine

pytestmark = [pytest.mark.level("unit"), pytest.mark.slow]


@pytest.fixture(scope="module")
def dense():
    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _greedy(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):].tolist()


def test_nucleus_mask_keeps_smallest_covering_prefix():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    # p=0.6: top-1 (0.5) leaves mass-before 0.5 < 0.6 for token 1 too; token
    # 2's preceding mass is 0.8 >= 0.6 → masked
    masked = np.asarray(nucleus_mask(logits, jnp.asarray([0.6])))
    assert np.isfinite(masked[0, :2]).all()
    assert (masked[0, 2:] < -1e29).all()
    # p→0 keeps exactly the argmax
    masked = np.asarray(nucleus_mask(logits, jnp.asarray([1e-6])))
    assert np.isfinite(masked[0, 0]) and (masked[0, 1:] < -1e29).all()
    # p=1.0 keeps everything
    masked = np.asarray(nucleus_mask(logits, jnp.asarray([1.0])))
    assert np.isfinite(masked).all()


def test_generate_top_p_tiny_equals_greedy(dense):
    params, cfg = dense
    prompt = [5, 17, 42, 99]
    want = _greedy(params, cfg, prompt, 8)
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=8, temperature=1.0, top_p=1e-6,
                   rng=jax.random.PRNGKey(7))
    assert np.asarray(out)[0, len(prompt):].tolist() == want


class TestEngineTopP:
    def test_tiny_top_p_reproduces_greedy_per_slot(self, dense):
        """One greedy slot and one hot-but-nucleus-collapsed slot share the
        compiled step; both must match the greedy solo run."""
        params, cfg = dense
        p1, p2 = [7, 8, 9], [100, 200, 300]
        w1, w2 = _greedy(params, cfg, p1, 6), _greedy(params, cfg, p2, 6)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(8,))
        h1 = eng.submit(p1, max_new_tokens=6)                    # greedy
        h2 = eng.submit(p2, max_new_tokens=6, temperature=1.0,
                        top_p=1e-6)                              # nucleus→top1
        while eng.step():
            pass
        assert h1.result(timeout=0) == w1
        assert h2.result(timeout=0) == w2

    def test_engine_default_top_p(self, dense):
        params, cfg = dense
        prompt = [3, 4, 5]
        want = _greedy(params, cfg, prompt, 5)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,), temperature=0.8,
                               top_p=1e-6)
        h = eng.submit(prompt, max_new_tokens=5)
        while eng.step():
            pass
        assert h.result(timeout=0) == want

    def test_late_nucleus_request_on_warm_engine(self, dense):
        """The sticky nucleus flag: an engine that has already compiled the
        no-top-p step accepts a top_p request afterwards (second compiled
        variant) and still decodes both correctly."""
        params, cfg = dense
        prompt = [9, 9, 2]
        want = _greedy(params, cfg, prompt, 5)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(8,))
        h0 = eng.submit(prompt, max_new_tokens=5)
        while eng.step():
            pass
        assert h0.result(timeout=0) == want
        h1 = eng.submit(prompt, max_new_tokens=5, temperature=1.0,
                        top_p=1e-6)
        while eng.step():
            pass
        assert h1.result(timeout=0) == want

    def test_top_p_validation(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(8,))
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1, 2], max_new_tokens=2, top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1, 2], max_new_tokens=2, top_p=1.5)
        # engine-level default is validated too (0.0 would mask ALL tokens)
        with pytest.raises(ValueError, match="top_p"):
            GenerationEngine(params, cfg, slots=1, max_len=32, top_p=0.0)

    def test_top_p_one_does_not_arm_nucleus(self, dense):
        """top_p=1.0 means 'disabled' — it must not compile in the
        full-vocab sort path."""
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(8,), top_p=1.0)
        assert eng._nucleus is False
        eng2 = GenerationEngine(params, cfg, slots=1, max_len=32,
                                prefill_buckets=(8,), top_p=0.9)
        assert eng2._nucleus is True


class TestStopSequences:
    def test_single_stop_sequence_ends_stream_at_match(self, dense):
        params, cfg = dense
        prompt = [5, 17, 42, 99]
        full = _greedy(params, cfg, prompt, 10)
        stop = full[3:5]
        # expected cut: the FIRST suffix match of the stop pair (weights/
        # seed changes may surface it earlier than position 3)
        first = next(i for i in range(len(full) - 1)
                     if full[i:i + 2] == stop)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=10, stop=[stop])
        while eng.step():
            pass
        assert h.result(timeout=0) == full[:first + 2]  # stop tokens emitted

    def test_single_token_stop_acts_like_eos(self, dense):
        params, cfg = dense
        prompt = [7, 8, 9]
        full = _greedy(params, cfg, prompt, 8)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        # a flat list of ints is ONE stop sequence
        h = eng.submit(prompt, max_new_tokens=8, stop=[full[2]])
        while eng.step():
            pass
        assert h.result(timeout=0) == full[:3]

    def test_multiple_stop_sequences_first_match_wins(self, dense):
        params, cfg = dense
        prompt = [1, 2]
        full = _greedy(params, cfg, prompt, 8)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=8,
                       stop=[[12345], full[1:3], full[4:6]])
        while eng.step():
            pass
        assert h.result(timeout=0) == full[:3]

    def test_no_match_runs_to_max_tokens(self, dense):
        params, cfg = dense
        prompt = [4, 4, 4]
        full = _greedy(params, cfg, prompt, 6)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=6, stop=[[123456789]])
        while eng.step():
            pass
        assert h.result(timeout=0) == full

    def test_stop_isolated_per_slot(self, dense):
        """A stop sequence on one request must not clip its neighbor."""
        params, cfg = dense
        p1, p2 = [7, 8, 9], [100, 200, 300]
        w1, w2 = _greedy(params, cfg, p1, 6), _greedy(params, cfg, p2, 6)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(8,))
        h1 = eng.submit(p1, max_new_tokens=6, stop=[w1[1:3]])
        h2 = eng.submit(p2, max_new_tokens=6)
        while eng.step():
            pass
        assert h1.result(timeout=0) == w1[:3]
        assert h2.result(timeout=0) == w2

    def test_numpy_token_ids_accepted(self, dense):
        """Tokenizer pipelines hand numpy ids; a flat numpy array is ONE
        stop sequence, same as a flat list of python ints."""
        params, cfg = dense
        prompt = [7, 8, 9]
        full = _greedy(params, cfg, prompt, 8)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=8,
                       stop=np.asarray(full[2:4]))
        while eng.step():
            pass
        first = next(i for i in range(len(full) - 1)
                     if full[i:i + 2] == full[2:4])
        assert h.result(timeout=0) == full[:first + 2]

    def test_empty_stop_sequence_rejected(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(8,))
        with pytest.raises(ValueError, match="stop"):
            eng.submit([1, 2], max_new_tokens=2, stop=[[]])


def test_spec_engine_stop_and_top_p_refusal(dense):
    from kubetorch_tpu.serve import SpeculativeEngine

    params, cfg = dense
    draft = llama_init(jax.random.PRNGKey(1), cfg)
    full = None
    eng = SpeculativeEngine(params, cfg, draft, cfg, spec_k=2, slots=2,
                            max_len=64, prefill_buckets=(8,))
    prompt = [5, 17, 42, 99]
    h = eng.submit(prompt, max_new_tokens=10)
    while eng.step():
        pass
    full = h.result(timeout=0)
    h2 = eng.submit(prompt, max_new_tokens=10, stop=[full[3:5]])
    while eng.step():
        pass
    assert h2.result(timeout=0) == full[:5]
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(prompt, max_new_tokens=4, top_p=0.5)
    # the engine-wide kwarg is refused at construction, same as temperature
    with pytest.raises(ValueError, match="top_p"):
        SpeculativeEngine(params, cfg, draft, cfg, spec_k=2, slots=2,
                          max_len=64, top_p=0.9)


class TestLogprobs:
    def test_greedy_logprobs_match_forward_oracle(self, dense):
        """handle.logprobs[i] must equal log_softmax(logits) at the chosen
        token, where logits come from an independent full forward over
        prompt + completion."""
        from kubetorch_tpu.models.llama import llama_forward

        params, cfg = dense
        prompt = [5, 17, 42, 99]
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=6)
        while eng.step():
            pass
        toks = h.result(timeout=0)
        lps = h.logprobs
        assert len(lps) == len(toks) and all(lp is not None for lp in lps)
        full = jnp.asarray([prompt + toks], jnp.int32)
        logits = np.asarray(llama_forward(params, full, cfg))  # (1, T, V)
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        for i, (t, lp) in enumerate(zip(toks, lps)):
            want = logp[0, len(prompt) - 1 + i, t]
            assert abs(lp - want) < 1e-4, (i, lp, want)

    def test_streaming_alignment_mid_flight(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit([1, 2, 3], max_new_tokens=5)
        seen = []
        it = iter(h)
        while eng.step():
            pass
        for tok in it:
            seen.append(tok)
            lps = h.logprobs
            assert len(lps) == len(seen)      # never lags the stream
        assert len(seen) == 5

    def test_spec_engine_logprobs_are_none(self, dense):
        from kubetorch_tpu.serve import SpeculativeEngine

        params, cfg = dense
        draft = llama_init(jax.random.PRNGKey(1), cfg)
        eng = SpeculativeEngine(params, cfg, draft, cfg, spec_k=2, slots=1,
                                max_len=64, prefill_buckets=(8,))
        h = eng.submit([5, 17], max_new_tokens=4)
        while eng.step():
            pass
        toks = h.result(timeout=0)
        lps = h.logprobs
        assert len(lps) == len(toks)
        # speculative emissions (admission + verify) don't compute logprobs
        assert all(lp is None for lp in lps)


class TestRepetitionPenalties:
    def test_huge_presence_penalty_never_repeats(self, dense):
        """With an overwhelming presence penalty, greedy decode can never
        emit a token it has already seen (prompt included)."""
        params, cfg = dense
        prompt = [5, 17, 42]
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=10, presence_penalty=1e9)
        while eng.step():
            pass
        toks = h.result(timeout=0)
        seen = set(prompt)
        for t in toks:
            assert t not in seen, (t, toks)
            seen.add(t)

    def test_zero_penalty_neighbor_is_bit_exact(self, dense):
        """A penalized slot must not perturb its zero-penalty neighbor even
        though the counts buffer is live for the whole grid."""
        params, cfg = dense
        p1, p2 = [7, 8, 9], [100, 200, 300]
        w2 = _greedy(params, cfg, p2, 6)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(8,))
        h1 = eng.submit(p1, max_new_tokens=6, frequency_penalty=5.0)
        h2 = eng.submit(p2, max_new_tokens=6)
        while eng.step():
            pass
        h1.result(timeout=0)
        assert h2.result(timeout=0) == w2

    def test_first_token_respects_prompt_counts(self, dense):
        """The prompt is 'text so far': the token a solo run would pick
        first, if placed in the prompt, must be avoided under a huge
        presence penalty — starting from the very first sample."""
        params, cfg = dense
        prompt = [4, 4, 4]
        solo_first = _greedy(params, cfg, prompt, 1)[0]
        prompt2 = prompt + [solo_first]
        # make sure the construction is meaningful: the natural first
        # token of prompt2 may differ; assert only the penalty guarantee
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt2, max_new_tokens=3, presence_penalty=1e9)
        while eng.step():
            pass
        toks = h.result(timeout=0)
        assert toks[0] not in set(prompt2)

    def test_slot_reuse_clears_penalties(self, dense):
        """After a penalized request retires, the next occupant of the same
        slot with no penalties matches its solo run (stale counts rows are
        neutralized by zero penalty vectors)."""
        params, cfg = dense
        prompt = [1, 2, 3]
        want = _greedy(params, cfg, prompt, 5)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h1 = eng.submit([9, 9], max_new_tokens=4, frequency_penalty=3.0)
        while eng.step():
            pass
        h1.result(timeout=0)
        h2 = eng.submit(prompt, max_new_tokens=5)
        while eng.step():
            pass
        assert h2.result(timeout=0) == want

    def test_spec_engine_refuses_penalties(self, dense):
        from kubetorch_tpu.serve import SpeculativeEngine

        params, cfg = dense
        draft = llama_init(jax.random.PRNGKey(1), cfg)
        eng = SpeculativeEngine(params, cfg, draft, cfg, spec_k=2, slots=1,
                                max_len=64, prefill_buckets=(8,))
        with pytest.raises(ValueError, match="penalt"):
            eng.submit([1, 2], max_new_tokens=2, presence_penalty=0.5)

    def test_logprobs_stay_raw_under_penalties(self, dense):
        """Penalties steer the CHOICE; the reported logprob is still the
        raw model's score for whatever token was chosen."""
        from kubetorch_tpu.models.llama import llama_forward

        params, cfg = dense
        prompt = [5, 17, 42]
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=5, presence_penalty=1e9)
        while eng.step():
            pass
        toks = h.result(timeout=0)
        lps = h.logprobs
        full = jnp.asarray([prompt + toks], jnp.int32)
        logits = np.asarray(llama_forward(params, full, cfg))
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        for i, (t, lp) in enumerate(zip(toks, lps)):
            want = logp[0, len(prompt) - 1 + i, t]
            assert abs(lp - want) < 1e-4, (i, lp, want)
