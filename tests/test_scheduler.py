"""Preemptive, priority-tiered scheduling (ISSUE 8): the admission queue +
capacity book in front of every controller placement, preemption via the
PR 6 drain path (SIGTERM → ``kt.drain_requested()`` → ``Checkpointer``
commit inside the grace window), and transparent checkpoint-resume when
capacity frees — ``make test-sched``.

The acceptance scenario rides REAL processes: a numpy training loop in a
subprocess is preempted through the shared SIGTERM+grace+SIGKILL contract
(``chaos.deliver_term_with_grace`` — the same delivery the ``term-rank``
chaos verb uses), commits inside the window, and resumes with a
``tree_fingerprint`` matching a clean reload and zero lost committed steps.
"""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.level("minimal"), pytest.mark.sched]

from kubetorch_tpu.controller.app import ControllerState
from kubetorch_tpu.controller.scheduler import (
    _PREEMPTIONS, CapacityBook, CostPolicy, MaxMinFairnessPolicy, Scheduler,
    SchedulingPolicy, _class_from_manifest, _parse_capacity,
    _shrunk_mesh_env, parse_priority, tier_of)
from kubetorch_tpu.train import checkpoint as ck
from tests.assets.threaded_server import ThreadedAiohttpServer

ASSETS = os.path.join(os.path.dirname(__file__), "assets")


def _store_app(root):
    from kubetorch_tpu.data_store.store_server import create_store_app
    return lambda: create_store_app(str(root))


class FakeBackend:
    """Just enough backend for the scheduler: applies are bookkeeping,
    ``signal_pods`` drains instantly when cooperative (the pods 'commit and
    exit') and never when not (the forced-eviction case)."""

    server_port = 32300

    def __init__(self, cooperative=True):
        self.pods = {}
        self.applies = []
        self.signals = []
        self.cooperative = cooperative

    def apply(self, ns, name, manifest, env):
        key = f"{ns}/{name}"
        replicas = int((manifest.get("spec") or {}).get("replicas", 1))
        self.applies.append((key, replicas, dict(env)))
        self.pods[key] = replicas
        return {"pod_ips": [f"10.0.0.{i}" for i in range(replicas)],
                "service_url": (f"http://10.0.0.0:{self.server_port}"
                                if replicas else None)}

    def pod_ips(self, ns, name):
        return [f"10.0.0.{i}"
                for i in range(self.pods.get(f"{ns}/{name}", 0))]

    def signal_pods(self, ns, name, sig, grace_s=0.0):
        key = f"{ns}/{name}"
        self.signals.append((key, sig, grace_s))
        if self.cooperative:
            self.pods[key] = 0        # drained: committed and exited
        return 1

    def delete(self, ns, name, kind=None):
        return self.pods.pop(f"{ns}/{name}", None) is not None

    def shutdown(self):
        pass


def _state(backend, capacity, policy=None, state_dir=None):
    state = ControllerState(backend=backend, state_dir=state_dir)
    state.scheduler = Scheduler(state, capacity=capacity, policy=policy)
    return state


def _rec(state, name, width, priority=None, device_class="cpu",
         metadata=None, drain_grace_s=None, ns="default"):
    sched = {"device_class": device_class, "width": width}
    if priority is not None:
        sched["priority"] = priority
    if drain_grace_s is not None:
        sched["drain_grace_s"] = drain_grace_s
    record = {"namespace": ns, "name": name,
              "manifest": {"kind": "Deployment",
                           "spec": {"replicas": width}},
              "metadata": metadata or {}, "launch_id": name,
              "created_at": time.time(), "updated_at": time.time(),
              "scheduling": sched}
    state.workloads[f"{ns}/{name}"] = record
    return record


async def _submit(state, record):
    return await state.sched().submit(
        record, record["manifest"], {})


# ---------------------------------------------------------------------------
# Tiers, capacity book, demand inference
# ---------------------------------------------------------------------------


def test_parse_priority_and_tier_bands():
    assert parse_priority("high") == 80 and tier_of(80) == "high"
    assert parse_priority("batch") == 20 and tier_of(20) == "batch"
    assert parse_priority(None) == 50 and tier_of(50) == "normal"
    assert parse_priority("junk") == 50       # unparseable → default
    assert parse_priority(999) == 100 and parse_priority(-3) == 0
    assert tier_of(69) == "normal" and tier_of(70) == "high"
    assert tier_of(39) == "batch" and tier_of(40) == "normal"


def test_capacity_env_parsing_skips_malformed_tokens():
    assert _parse_capacity("cpu=8,v5e=16") == {"cpu": 8, "v5e": 16}
    assert _parse_capacity(" cpu = 4 ,junk,v5p=oops,v5e=-2") == \
        {"cpu": 4, "v5e": 0}
    assert _parse_capacity(None) == {} and _parse_capacity("") == {}


def test_capacity_book_accounting():
    book = CapacityBook({"cpu": 4, "v5e": 8})
    assert book.limited and book.free("cpu") == 4
    book.allocate("d/a", "cpu", 3, 20)
    assert book.free("cpu") == 1 and book.fits("cpu", 1)
    assert not book.fits("cpu", 2)
    assert book.free("v5p") == 0            # unlisted class doesn't exist
    book.resize("d/a", 2)
    assert book.free("cpu") == 2
    assert book.release("d/a")["width"] == 2
    assert book.free("cpu") == 4 and book.release("d/a") is None
    # unlimited book: everything fits, free is None
    assert not CapacityBook().limited
    assert CapacityBook().fits("v5p", 10 ** 6)


def test_demand_inferred_from_gke_selector():
    manifest = {"spec": {"replicas": 4, "template": {"spec": {
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "2x4"}}}}}
    assert _class_from_manifest(manifest) == "v5e"
    assert _class_from_manifest({"spec": {}}) == "cpu"
    cls, width = Scheduler.demand_for(
        {"scheduling": None, "manifest": manifest})
    assert (cls, width) == ("v5e", 4)
    # explicit scheduling block wins over inference
    cls, width = Scheduler.demand_for(
        {"scheduling": {"device_class": "v5p", "width": 2},
         "manifest": manifest})
    assert (cls, width) == ("v5p", 2)


# ---------------------------------------------------------------------------
# Admission: pass-through, queueing, preemption
# ---------------------------------------------------------------------------


def test_unlimited_book_is_pass_through():
    fb = FakeBackend()
    state = _state(fb, capacity={})

    async def go():
        a = _rec(state, "a", 3)
        out = await _submit(state, a)
        assert "queued" not in out and len(out["pod_ips"]) == 3
        assert not state.sched().queue
        assert state.sched().book.allocations["default/a"]["width"] == 3

    asyncio.run(go())


def test_full_book_queues_same_tier():
    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 2})

    async def go():
        await _submit(state, _rec(state, "a", 2, priority="batch"))
        out = await _submit(state, _rec(state, "b", 1, priority="batch"))
        assert out["queued"] and out["tier"] == "batch"
        assert out["position"] == 0
        assert state.workloads["default/b"]["status"] == "queued"
        assert not fb.signals, "same tier must never preempt"
        # b placed automatically once a releases its slots
        state.workloads.pop("default/a")
        await state.sched().release("default", "a")
        await state.sched().kick()
        assert not state.sched().queue
        assert state.sched().book.allocations["default/b"]["width"] == 1
        assert "status" not in state.workloads["default/b"]

    asyncio.run(go())


def test_higher_tier_preempts_batch_via_drain_path():
    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 2})

    async def go():
        import signal
        await _submit(state, _rec(state, "batchjob", 2, priority="batch",
                                  drain_grace_s=5.0))
        before = _PREEMPTIONS.value(tier="batch", outcome="drained")
        out = await _submit(state, _rec(state, "serve", 2, priority="high"))
        # the high-tier deploy PLACED (not queued) by evicting the batch job
        assert "queued" not in out and len(out["pod_ips"]) == 2
        assert fb.signals == [("default/batchjob", signal.SIGTERM, 5.0)]
        assert _PREEMPTIONS.value(tier="batch",
                                  outcome="drained") == before + 1
        # victim: evicted (scaled to 0), re-queued at its own priority
        assert fb.pods["default/batchjob"] == 0
        assert state.workloads["default/batchjob"]["status"] == "preempted"
        [entry] = state.sched().queue
        assert entry["key"] == "default/batchjob" and entry["preempted"]
        assert entry["priority"] == 20 and entry["width"] == 2
        led = state.sched().ledger[-1]
        assert led["phase"] == "evicted" and led["drained"] is True
        assert led["preemptor"] == "default/serve"

        # transparent resume: delete the preemptor → victim re-places
        state.workloads.pop("default/serve")
        await state.sched().release("default", "serve")
        await state.sched().kick()
        assert not state.sched().queue
        assert fb.pods["default/batchjob"] == 2
        assert state.sched().ledger[-1]["phase"] == "resumed"
        assert "status" not in state.workloads["default/batchjob"]

    asyncio.run(go())


def test_same_tier_and_lower_tier_never_preempt():
    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 1})

    async def go():
        await _submit(state, _rec(state, "a", 1, priority="normal"))
        # higher priority NUMBER, same tier → queue, don't evict
        out = await _submit(state, _rec(state, "b", 1, priority=65))
        assert out["queued"] and not fb.signals
        # lower tier → queue
        out = await _submit(state, _rec(state, "c", 1, priority="batch"))
        assert out["queued"] and not fb.signals

    asyncio.run(go())


def test_forced_eviction_when_pods_ignore_sigterm():
    fb = FakeBackend(cooperative=False)       # pods squat past the grace
    state = _state(fb, capacity={"cpu": 1})

    async def go():
        await _submit(state, _rec(state, "stubborn", 1, priority="batch",
                                  drain_grace_s=0.3))
        before = _PREEMPTIONS.value(tier="batch", outcome="forced")
        t0 = time.monotonic()
        out = await _submit(state, _rec(state, "vip", 1, priority="high"))
        assert "queued" not in out
        assert time.monotonic() - t0 >= 0.3   # the grace window was granted
        assert _PREEMPTIONS.value(tier="batch",
                                  outcome="forced") == before + 1
        led = state.sched().ledger[-1]
        assert led["drained"] is False and led["phase"] == "evicted"
        # the eviction (apply replicas=0) is the backstop for squatters
        assert ("default/stubborn", 0) in [(k, r)
                                           for k, r, _ in fb.applies]

    asyncio.run(go())


def test_reduced_width_resume_shrinks_mesh():
    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 4})

    async def go():
        meta = {"KT_DISTRIBUTED_CONFIG": {
            "distribution_type": "spmd", "workers": 4,
            "mesh": {"data": 4}}}
        await _submit(state, _rec(state, "widejob", 4, priority="batch",
                                  metadata=meta))
        await _submit(state, _rec(state, "vip", 2, priority="high"))
        # widejob evicted and queued at width 4; only 2 slots remain free
        assert state.sched().queue[0]["width"] == 4
        assert state.sched().book.free("cpu") == 2
        await state.sched().kick()
        # resumed at reduced width with the mesh re-solved (data 4 → 2)
        assert not state.sched().queue
        alloc = state.sched().book.allocations["default/widejob"]
        assert alloc["width"] == 2
        key, replicas, env = fb.applies[-1]
        assert key == "default/widejob" and replicas == 2
        assert json.loads(env["KT_MESH"]) == {"data": 2}

    asyncio.run(go())


def test_mesh_that_cannot_shrink_stays_queued():
    # tensor=4 needs all 4 devices: no reduced-width placement exists
    record = {"metadata": {"KT_DISTRIBUTED_CONFIG": {"mesh": {"tensor": 4}}}}
    assert _shrunk_mesh_env(record, 4, 2) is None
    # no declared mesh: plain replicas shrink freely (empty override)
    assert _shrunk_mesh_env({"metadata": {}}, 4, 2) == {}

    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 4})

    async def go():
        meta = {"KT_DISTRIBUTED_CONFIG": {"mesh": {"tensor": 4}}}
        await _submit(state, _rec(state, "tp", 4, priority="batch",
                                  metadata=meta))
        await _submit(state, _rec(state, "vip", 2, priority="high"))
        await state.sched().kick()
        # still queued: 2 free slots can't hold a tensor=4 program
        assert state.sched().queue[0]["key"] == "default/tp"
        # preemptor done → full width frees → tp resumes at 4
        state.workloads.pop("default/vip")
        await state.sched().release("default", "vip")
        await state.sched().kick()
        assert not state.sched().queue
        assert state.sched().book.allocations["default/tp"]["width"] == 4

    asyncio.run(go())


def test_initial_scale_zero_charges_no_slots():
    """An autoscaling deploy with initial_scale=0 places ZERO pods — the
    book must not charge a phantom slot for it (the slot materializes at
    cold start, through the scale path)."""
    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 2})

    async def go():
        rec = _rec(state, "lazy", 1, priority="batch")
        rec["autoscaling"] = {"min_scale": 0, "initial_scale": 0}
        rec["manifest"]["spec"]["replicas"] = 0
        rec["expected_pods"] = 0
        out = await _submit(state, rec)
        assert "queued" not in out
        assert state.sched().book.used("cpu") == 0
        await state.sched().scale(rec, 1, "cold start")
        assert state.sched().book.used("cpu") == 1

    asyncio.run(go())


def test_autoscale_scale_up_clamps_to_capacity():
    fb = FakeBackend()
    state = _state(fb, capacity={"cpu": 3})

    async def go():
        rec = _rec(state, "svc", 1, priority="normal")
        await _submit(state, rec)
        await state.sched().scale(rec, 5, "inflight burst")
        # clamped to the book: 1 running + 2 free
        assert state.sched().book.allocations["default/svc"]["width"] == 3
        assert fb.pods["default/svc"] == 3
        assert any("clamped" in e["message"] for e in state.events)
        # scale to zero frees everything
        await state.sched().scale(rec, 0, "idle")
        assert "default/svc" not in state.sched().book.allocations
        assert rec["scaled_to_zero"]

    asyncio.run(go())


# ---------------------------------------------------------------------------
# Policies & heterogeneity-aware scoring
# ---------------------------------------------------------------------------


def test_throughput_ewma_and_static_fallback():
    state = _state(FakeBackend(), capacity={})
    s = state.sched()
    s.note_throughput("d/j", "v5e", execute_sum=10.0, execute_count=100)
    assert s.throughput_score("d/j", "v5e") == pytest.approx(10.0)
    s.note_throughput("d/j", "v5e", execute_sum=5.0, execute_count=100)
    assert s.throughput_score("d/j", "v5e") == pytest.approx(13.0)  # EWMA
    # unmeasured class: scaled by the static speed ratio off the anchor
    v5p = s.throughput_score("d/j", "v5p")
    assert v5p == pytest.approx(13.0 * 459 / 197)
    # a workload with no measurements at all falls back to the prior
    assert s.throughput_score("d/x", "cpu") == 1.0
    assert s.throughput_score("d/x", "v5e") == pytest.approx(197.0)


def test_fifo_priority_order_resume_before_new():
    state = _state(FakeBackend(), capacity={})
    pol = SchedulingPolicy()
    q = [{"key": "a", "priority": 50, "seq": 1},
         {"key": "b", "priority": 80, "seq": 2},
         {"key": "c", "priority": 50, "seq": 3, "preempted": True},
         {"key": "d", "priority": 50, "seq": 4}]
    assert [e["key"] for e in pol.order(q, state.sched())] == \
        ["b", "c", "a", "d"]


def test_max_min_fairness_orders_by_accumulated_service():
    state = _state(FakeBackend(), capacity={}, policy="max-min-fairness")
    s = state.sched()
    assert isinstance(s.policy, MaxMinFairnessPolicy)
    s._service = {"d/greedy": 500.0, "d/starved": 1.0}
    q = [{"key": "d/greedy", "priority": 20, "seq": 1},
         {"key": "d/starved", "priority": 20, "seq": 2},
         {"key": "d/vip", "priority": 80, "seq": 3}]
    # tier still dominates; within the batch tier the starved job wins
    assert [e["key"] for e in s.policy.order(q, s)] == \
        ["d/vip", "d/starved", "d/greedy"]


def test_cost_policy_picks_cheapest_adequate_class(monkeypatch):
    monkeypatch.setenv("KT_SCHED_COST", "v5e=1.2,v5p=4.2")
    state = _state(FakeBackend(), capacity={"v5e": 8, "v5p": 8})
    s = state.sched()
    s.note_throughput("d/j", "v5e", execute_sum=10.0, execute_count=100)
    entry = {"key": "d/j", "priority": 20, "seq": 1, "device_class": "v5e",
             "width": 2}
    candidates = {"v5e": 8, "v5p": 8}
    # throughput-only (default policy): v5p wins on the speed ratio
    assert SchedulingPolicy().choose_class(entry, candidates, s) == "v5p"
    # per-dollar: 10/1.2 ops/$ on v5e beats (10·459/197)/4.2 on v5p
    assert CostPolicy().choose_class(entry, candidates, s) == "v5e"


# ---------------------------------------------------------------------------
# End-to-end (in-process): preempt → drain-commit → evict → resume, with a
# REAL Checkpointer against a real store server
# ---------------------------------------------------------------------------


class ThreadTrainerBackend(FakeBackend):
    """'Pods' for the batch job are a thread running a genuine numpy
    training loop on the commit-marker protocol; ``signal_pods`` delivers
    the drain (the thread commits and exits, exactly what a SIGTERM'd rank
    does — the real signal plumbing is proven by the subprocess acceptance
    test below and test_elastic's term-rank e2e)."""

    def __init__(self, store_url, ckpt_key, trainee="batchjob"):
        super().__init__()
        self.store_url, self.ckpt_key, self.trainee = \
            store_url, ckpt_key, trainee
        self.threads = {}
        self.drain_events = {}
        self.observed = {}      # the trainer's self-reported state

    def apply(self, ns, name, manifest, env):
        key = f"{ns}/{name}"
        replicas = int((manifest.get("spec") or {}).get("replicas", 1))
        self.applies.append((key, replicas, dict(env)))
        if name != self.trainee:
            self.pods[key] = replicas
            return {"pod_ips": [f"10.1.0.{i}" for i in range(replicas)]}
        if replicas == 0:
            ev = self.drain_events.get(key)
            if ev is not None:
                ev.set()
            t = self.threads.get(key)
            if t is not None:
                t.join(timeout=10)
            self.pods[key] = 0
            return {"pod_ips": []}
        ev = threading.Event()
        self.drain_events[key] = ev
        t = threading.Thread(target=self._train, args=(key, ev),
                             daemon=True)
        self.threads[key] = t
        t.start()
        self.pods[key] = replicas
        return {"pod_ips": [f"10.1.0.{i}" for i in range(replicas)]}

    def pod_ips(self, ns, name):
        key = f"{ns}/{name}"
        if name == self.trainee:
            t = self.threads.get(key)
            return ["10.1.0.0"] if t is not None and t.is_alive() else []
        return super().pod_ips(ns, name)

    def signal_pods(self, ns, name, sig, grace_s=0.0):
        key = f"{ns}/{name}"
        self.signals.append((key, sig, grace_s))
        ev = self.drain_events.get(key)
        if ev is not None:
            ev.set()
            return 1
        return super().signal_pods(ns, name, sig, grace_s)

    def _train(self, key, drain_ev):
        ckpt = ck.Checkpointer(self.ckpt_key, store_url=self.store_url,
                               every=10 ** 9)   # periodic commits OFF
        restored = ckpt.restore()
        if restored is not None:
            tree, step = restored
            params, resumed_from = tree["w"], step
        else:
            params, step, resumed_from = np.zeros(8, np.float64), 0, None
        while not drain_ev.is_set():
            params = params + 1.0
            step += 1
            self.observed[key] = {
                "step": step, "resumed_from": resumed_from,
                "fingerprint": ck.tree_fingerprint({"w": params})}
            time.sleep(0.02)
        # the grace window: flush + commit, then vacate
        ckpt.flush()
        ckpt.save({"w": params}, step)
        self.observed[key] = {
            "step": step, "resumed_from": resumed_from, "drained": True,
            "fingerprint": ck.tree_fingerprint({"w": params})}


def _wait(predicate, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_preempt_drain_commit_resume_end_to_end(tmp_path):
    """The full scheduler loop in-process: a batch trainer (real
    ``Checkpointer``, real store server, periodic commits OFF) is preempted
    by a high-tier deploy; its ONLY commit is the drain-path one, landing
    inside the grace window; after the high-tier workload finishes it
    resumes automatically from exactly that step with a fingerprint
    matching a clean reload — zero committed steps lost."""
    with ThreadedAiohttpServer(_store_app(tmp_path / "store")) as srv:
        key = "sched/e2e"
        fb = ThreadTrainerBackend(srv.url, key)
        state = _state(fb, capacity={"cpu": 2})
        bkey = "default/batchjob"

        async def phase1():
            await _submit(state, _rec(state, "batchjob", 2,
                                      priority="batch", drain_grace_s=15.0))
            assert await asyncio.to_thread(
                _wait, lambda: fb.observed.get(bkey, {}).get("step", 0) >= 3)
            assert ck.commit_info(key, store_url=srv.url) is None, \
                "no commit may exist before the drain"
            # the preemptor: placement blocks until the victim drained
            out = await _submit(state, _rec(state, "serve", 2,
                                            priority="high"))
            assert "queued" not in out

        asyncio.run(phase1())
        drained = fb.observed[bkey]
        assert drained.get("drained"), "victim never took the drain path"
        info = ck.commit_info(key, store_url=srv.url)
        assert info is not None and info["step"] == drained["step"], \
            "the drain-path commit must capture the LAST completed step"
        assert state.sched().ledger[-1]["drained"] is True

        async def phase2():
            # preemptor finishes → the batch job resumes, no manual steps
            state.workloads.pop("default/serve")
            await state.sched().release("default", "serve")
            await state.sched().kick()
            assert await asyncio.to_thread(
                _wait, lambda: fb.observed.get(bkey, {}).get(
                    "resumed_from") == drained["step"])

        asyncio.run(phase2())
        # zero lost steps + bit-identical state: a clean reload of the
        # committed checkpoint fingerprints the drained params exactly
        reloaded, step = ck.Checkpointer(key, store_url=srv.url).restore()
        assert step == drained["step"]
        assert ck.tree_fingerprint(reloaded) == drained["fingerprint"]
        assert _wait(lambda: fb.observed[bkey].get("step", 0)
                     > drained["step"])
        # teardown the resumed trainer thread
        asyncio.run(state.sched().scale(
            state.workloads[bkey], 0, "test teardown"))


# ---------------------------------------------------------------------------
# The chaos acceptance: a REAL subprocess preempted through the REAL signal
# path (install_sigterm_drain + deliver_term_with_grace — the term-rank
# contract), then resumed by the scheduler
# ---------------------------------------------------------------------------


class SubprocessTrainerBackend(FakeBackend):
    """The batch job's pod is a real OS process running
    ``tests/assets/preemptible_trainer.py``; preemption delivers the
    SIGTERM + grace-window SIGKILL pair via the shared chaos contract."""

    def __init__(self, store_url, ckpt_key, trainee="batchjob"):
        super().__init__()
        self.store_url, self.ckpt_key, self.trainee = \
            store_url, ckpt_key, trainee
        self.procs = {}

    def _env(self):
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.pop("KT_CHAOS", None)
        # the package parent, so the subprocess imports THIS checkout
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(ck.__file__))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        return env

    def apply(self, ns, name, manifest, env):
        key = f"{ns}/{name}"
        replicas = int((manifest.get("spec") or {}).get("replicas", 1))
        self.applies.append((key, replicas, dict(env)))
        if name != self.trainee:
            self.pods[key] = replicas
            return {"pod_ips": [f"10.2.0.{i}" for i in range(replicas)]}
        proc = self.procs.get(key)
        if replicas == 0:
            if proc is not None and proc.poll() is None:
                proc.kill()
            self.pods[key] = 0
            return {"pod_ips": []}
        if proc is None or proc.poll() is not None:
            self.procs[key] = subprocess.Popen(
                [sys.executable,
                 os.path.join(ASSETS, "preemptible_trainer.py"),
                 self.store_url, self.ckpt_key, "0.05"],
                env=self._env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
        self.pods[key] = replicas
        return {"pod_ips": ["10.2.0.0"]}

    def pod_ips(self, ns, name):
        key = f"{ns}/{name}"
        if name == self.trainee:
            proc = self.procs.get(key)
            return ["10.2.0.0"] if proc is not None and \
                proc.poll() is None else []
        return super().pod_ips(ns, name)

    def signal_pods(self, ns, name, sig, grace_s=0.0):
        key = f"{ns}/{name}"
        self.signals.append((key, sig, grace_s))
        proc = self.procs.get(key)
        if proc is not None and proc.poll() is None:
            from kubetorch_tpu.chaos import deliver_term_with_grace
            deliver_term_with_grace(proc.pid, grace_s or 10.0,
                                    label=f"scheduler preemption of {key}")
            return 1
        return super().signal_pods(ns, name, sig, grace_s)

    def cleanup(self):
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.kill()


@pytest.mark.chaos
def test_scheduler_preemption_acceptance_subprocess(tmp_path):
    """THE acceptance scenario, with a full capacity book and real
    processes: deploying a higher-tier workload preempts the running batch
    job through the drain path (SIGTERM + grace-window SIGKILL — the
    term-rank contract), the batch job's checkpoint commits inside the
    grace window, and after the high-tier workload finishes the batch job
    resumes automatically with ``tree_fingerprint`` matching a clean
    reload and zero lost committed steps."""
    from kubetorch_tpu.data_store import commands as ds

    with ThreadedAiohttpServer(_store_app(tmp_path / "store")) as srv:
        key = "sched/acceptance"
        fb = SubprocessTrainerBackend(srv.url, key)
        state = _state(fb, capacity={"cpu": 2})
        try:
            async def phase1():
                await _submit(state, _rec(state, "batchjob", 2,
                                          priority="batch",
                                          drain_grace_s=20.0))
                # real subprocess: wait for real steps to land on the store
                assert await asyncio.to_thread(_wait, lambda: (
                    ds.get_json(f"{key}/__status__", store_url=srv.url)
                    or {}).get("step", 0) >= 3, 60.0)
                assert ck.commit_info(key, store_url=srv.url) is None
                out = await _submit(state, _rec(state, "serve", 2,
                                                priority="high"))
                assert "queued" not in out

            asyncio.run(phase1())
            # the grace window worked: the subprocess committed + vacated
            drained = ds.get_json(f"{key}/__drained__", store_url=srv.url)
            assert drained is not None and drained["reason"] == "SIGTERM"
            info = ck.commit_info(key, store_url=srv.url)
            assert info is not None and info["step"] == drained["step"]
            assert state.sched().ledger[-1]["drained"] is True
            last_status = ds.get_json(f"{key}/__status__",
                                      store_url=srv.url)
            assert last_status["step"] == drained["step"], \
                "zero completed steps may be lost"

            async def phase2():
                state.workloads.pop("default/serve")
                await state.sched().release("default", "serve")
                await state.sched().kick()
                assert await asyncio.to_thread(_wait, lambda: (
                    ds.get_json(f"{key}/__status__", store_url=srv.url)
                    or {}).get("resumed_from") == drained["step"], 60.0)

            asyncio.run(phase2())
            # the resumed process restored the EXACT committed bytes: its
            # first post-resume fingerprint is the committed params + 1.0,
            # and a clean reload matches the pre-preemption fingerprint
            reloaded, step = ck.Checkpointer(key,
                                             store_url=srv.url).restore()
            assert step == drained["step"]
            assert ck.tree_fingerprint(reloaded) == \
                last_status["fingerprint"]
            status = ds.get_json(f"{key}/__status__", store_url=srv.url)
            assert status["step"] > drained["step"]
        finally:
            fb.cleanup()
