"""Secrets delivered by reference, never by value (round-2 VERDICT #2).

Reference analog: ``resources/secrets/kubernetes_secrets_client.py`` — the
controller owns real K8s Secret objects; pod templates reference them via
``envFrom`` and Secret volume mounts. Local backend analog: 0600 files under
``~/.kt/secrets``, resolved at pod spawn. The non-negotiable property tested
end-to-end here: the pod sees the value, persisted controller state does not.
"""

import json
import os
import stat
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "assets"))

import kubetorch_tpu as kt
from kubetorch_tpu.resources.secret import Secret

import payloads  # tests/assets

SENTINEL = "s3kr1t-sauce-8f2a"


class TestManifestRefs:
    """Unit tier: secret references in the built manifests, no values."""

    def test_pod_template_env_refs_and_mount(self):
        from kubetorch_tpu.provisioning.manifests import build_pod_template

        spec = build_pod_template(
            "web", "python:3.11", {}, cpus="1",
            secrets=[{"name": "tok", "mount_path": None, "keys": ["API_KEY"]},
                     {"name": "plain-ref"},
                     {"name": "aws-secret",
                      "mount_path": "~/.aws/credentials",
                      "keys": ["AWS_ACCESS_KEY_ID"]}])
        container = spec["containers"][0]
        # known keys → per-key valueFrom (a blanket envFrom would also
        # inject the __file__ payload as env on Kubernetes)
        assert {"name": "API_KEY", "valueFrom": {"secretKeyRef": {
            "name": "tok", "key": "API_KEY"}}} in container["env"]
        assert {"name": "AWS_ACCESS_KEY_ID", "valueFrom": {"secretKeyRef": {
            "name": "aws-secret",
            "key": "AWS_ACCESS_KEY_ID"}}} in container["env"]
        # name-only ref without a mount: keys unknown → envFrom fallback
        assert container["envFrom"] == [{"secretRef": {"name": "plain-ref"}}]
        vol = next(v for v in spec["volumes"] if v["name"] == "secret-aws-secret")
        assert vol["secret"]["secretName"] == "aws-secret-file"
        assert vol["secret"]["items"] == [{"key": "__file__",
                                           "path": "credentials"}]
        mount = next(m for m in container["volumeMounts"]
                     if m["name"] == "secret-aws-secret")
        # subPath overlay: only the credential file, not the whole dir
        assert mount["mountPath"] == "/root/.aws/credentials"
        assert mount["subPath"] == "credentials"
        assert mount["readOnly"] is True

    def test_compute_manifest_carries_no_values(self, monkeypatch):
        monkeypatch.setenv("TEST_API_TOKEN", SENTINEL)
        s = Secret.from_env(["TEST_API_TOKEN"], name="test-api")
        manifest = kt.Compute(cpus=1, secrets=[s]).manifest("svc", env={})
        blob = json.dumps(manifest)
        assert SENTINEL not in blob
        assert '"secretKeyRef": {"name": "test-api", "key": "TEST_API_TOKEN"}' in blob

    def test_clean_strips_secret_manifest_payload(self):
        from kubetorch_tpu.controller.persistence import _clean

        record = {"namespace": "ns", "name": "tok",
                  "manifest": {"kind": "Secret",
                               "stringData": {"K": SENTINEL},
                               "metadata": {"name": "tok"}}}
        cleaned = _clean(record)
        assert SENTINEL not in json.dumps(cleaned)
        assert cleaned["manifest"]["metadata"]["name"] == "tok"


class TestFromName:
    def test_binds_existing_and_raises_on_missing(self, monkeypatch):
        from kubetorch_tpu.exceptions import SecretNotFound
        from kubetorch_tpu.resources import secret as secret_mod

        class StubClient:
            def get_object(self, kind, ns, name):
                if (kind, name) == ("Secret", "tok"):
                    return {"metadata": {"name": "tok"}, "keys": ["A"]}
                return None

        monkeypatch.setattr(secret_mod, "controller_client",
                            lambda: StubClient())
        s = Secret.from_name("tok")
        assert s.name == "tok" and s.values == {}
        # by-reference binding: save() must be a NO-OP — applying this
        # value-less handle would wipe the existing cluster secret (and
        # Compute attaches call save automatically)
        assert s.save() == {"ok": True, "by_reference": True}
        with pytest.raises(SecretNotFound, match="nope"):
            Secret.from_name("nope")


class TestLocalSecretStore:
    """LocalBackend: values land in 0600 files, pods resolve envFrom refs."""

    def test_store_and_resolve(self, tmp_path):
        from kubetorch_tpu.controller.backends import LocalBackend
        from kubetorch_tpu.provisioning.manifests import (
            build_deployment_manifest, build_pod_template)

        be = LocalBackend("http://127.0.0.1:1", secrets_dir=str(tmp_path))
        out = be.apply("ns1", "tok", {
            "kind": "Secret", "metadata": {"name": "tok"},
            "stringData": {"MY_TOKEN": SENTINEL}}, {})
        assert out == {"kind": "Secret", "stored": True}
        # the file payload rides a companion <name>-file object
        # (Secret.save's split: the base object stays envFrom-safe)
        be.apply("ns1", "tok-file", {
            "kind": "Secret", "metadata": {"name": "tok-file"},
            "stringData": {"__file__": "filedata",
                           "__mount_path__": "~/.aws/credentials"}}, {})
        # values in 0600 files under a 0700 dir, not in memory
        sdir = tmp_path / "ns1__tok"
        assert stat.S_IMODE(os.stat(sdir).st_mode) == 0o700
        assert stat.S_IMODE(os.stat(sdir / "MY_TOKEN").st_mode) == 0o600
        assert (sdir / "MY_TOKEN").read_text() == SENTINEL
        assert SENTINEL not in json.dumps(be.objects)
        assert be.objects["Secret/ns1/tok"]["keys"] == ["MY_TOKEN"]

        pod = build_pod_template("web", "img", {}, secrets=[
            {"name": "tok", "mount_path": "~/.aws/credentials",
             "keys": ["MY_TOKEN"]}])
        env = be._secret_env("ns1", build_deployment_manifest(
            "web", "ns1", 1, pod))
        assert env["MY_TOKEN"] == SENTINEL
        fdir = tmp_path / "ns1__tok-file"
        assert env["KT_SECRET_FILE_TOK"] == str(fdir / "__file__")
        assert (fdir / "__file__").read_text() == "filedata"

        # delete removes the files
        assert be.delete("ns1", "tok") is True
        assert not sdir.exists()


@pytest.mark.slow
@pytest.mark.level("minimal")
class TestSecretE2E:
    def test_pod_sees_secret_state_does_not(self, monkeypatch):
        """from_env → deploy → remote fn reads the env var; the controller
        state dir never stores the value (VERDICT round 2 'done' bar)."""
        monkeypatch.setenv("KT_E2E_SECRET", SENTINEL)
        s = Secret.from_env(["KT_E2E_SECRET"], name="e2e-secret")
        f = kt.fn(payloads.echo_env)
        f.to(kt.Compute(cpus=1, secrets=[s]))
        try:
            result = f("KT_E2E_SECRET")
            assert result["KT_E2E_SECRET"] == SENTINEL

            state_dir = os.path.expanduser("~/.kt/controller-state")
            hits = []
            for root, _, files in os.walk(state_dir):
                for fname in files:
                    p = os.path.join(root, fname)
                    try:
                        with open(p, errors="ignore") as fh:
                            if SENTINEL in fh.read():
                                hits.append(p)
                    except OSError:
                        continue
            assert not hits, f"secret value leaked into state: {hits}"
        finally:
            f.teardown()
            s.delete()


class TestControllerSecretScrub:
    """The /controller/object read path must strip every field that can
    carry secret payload — on the k8s backend the object comes from
    ``kubectl get -o json`` after a client-side apply, whose
    last-applied-configuration annotation embeds the full stringData."""

    def test_scrub_drops_last_applied_annotation_and_managed_fields(self):
        from kubetorch_tpu.controller.app import _scrub_secret_object

        obj = {
            "kind": "Secret",
            "metadata": {
                "name": "tok",
                "labels": {"app": "x"},
                "annotations": {
                    "kubectl.kubernetes.io/last-applied-configuration":
                        json.dumps({"stringData": {"MY_TOKEN": SENTINEL}}),
                    "user/note": "keep-me",
                },
                "managedFields": [{"fieldsV1": {"f:stringData":
                                                {"f:MY_TOKEN": {}}}}],
            },
            "data": {"MY_TOKEN": "c2VjcmV0"},
            "stringData": {"MY_TOKEN": SENTINEL},
        }
        scrubbed = _scrub_secret_object(obj)
        dumped = json.dumps(scrubbed)
        assert SENTINEL not in dumped
        assert "MY_TOKEN" not in dumped
        # metadata that carries no payload survives
        assert scrubbed["metadata"]["name"] == "tok"
        assert scrubbed["metadata"]["labels"] == {"app": "x"}
        assert scrubbed["metadata"]["annotations"] == {"user/note": "keep-me"}

    def test_scrub_handles_missing_metadata(self):
        from kubetorch_tpu.controller.app import _scrub_secret_object

        assert _scrub_secret_object({"stringData": {"k": "v"}}) == {}


class TestWorkloadDeleteScope:
    """Deleting a workload must not wipe an independent Secret/PVC that
    merely shares its name (advisor round-3 finding)."""

    def test_same_name_secret_survives_workload_delete(self, tmp_path):
        from kubetorch_tpu.controller.backends import LocalBackend

        be = LocalBackend("http://127.0.0.1:1",
                          secrets_dir=str(tmp_path / "sec"),
                          volumes_dir=str(tmp_path / "vol"))
        be.apply("ns1", "shared", {
            "kind": "Secret", "metadata": {"name": "shared"},
            "stringData": {"MY_TOKEN": SENTINEL}}, {})
        sdir = tmp_path / "sec" / "ns1__shared"
        assert (sdir / "MY_TOKEN").read_text() == SENTINEL

        # a service later applied under the same ns/name (0 replicas: no
        # pods to spawn in a unit test), then deleted — the independent
        # Secret's object entry and files must be untouched
        be.apply("ns1", "shared", {
            "kind": "Deployment", "metadata": {"name": "shared"},
            "spec": {"replicas": 0}}, {})
        be.delete("ns1", "shared")
        assert be.objects["Secret/ns1/shared"]["keys"] == ["MY_TOKEN"]
        assert (sdir / "MY_TOKEN").read_text() == SENTINEL

        # a Secret deployed AS the workload is swept by workload delete
        be.apply("ns1", "shared2", {
            "kind": "Secret", "metadata": {"name": "shared2"},
            "stringData": {"T": "v"}}, {})
        assert be.delete("ns1", "shared2") is True
        assert "Secret/ns1/shared2" not in be.objects
        assert not (tmp_path / "sec" / "ns1__shared2").exists()

        # explicit object deletion still removes the files
        assert be.delete_object("Secret", "ns1", "shared") is True
        assert not sdir.exists()

    def test_secret_applied_after_workload_survives_its_delete(self, tmp_path):
        """Reverse apply order: the workload exists FIRST, then an
        independent Secret lands under the same name. The controller passes
        the record's manifest kind on delete, which must scope the sweep
        regardless of which apply came last."""
        from kubetorch_tpu.controller.backends import LocalBackend

        be = LocalBackend("http://127.0.0.1:1",
                          secrets_dir=str(tmp_path / "sec"),
                          volumes_dir=str(tmp_path / "vol"))
        be.apply("ns1", "shared", {
            "kind": "Deployment", "metadata": {"name": "shared"},
            "spec": {"replicas": 0}}, {})
        be.apply("ns1", "shared", {
            "kind": "Secret", "metadata": {"name": "shared"},
            "stringData": {"MY_TOKEN": SENTINEL}}, {})
        sdir = tmp_path / "sec" / "ns1__shared"

        # the controller's delete_workload path: kind comes from the durable
        # workload record, not the (single-slot, last-write-wins) kinds map
        be.delete("ns1", "shared", kind="Deployment")
        assert be.objects["Secret/ns1/shared"]["keys"] == ["MY_TOKEN"]
        assert (sdir / "MY_TOKEN").read_text() == SENTINEL


class TestPodEnvHygiene:
    """A controller accidentally started from a pod environment must not
    stamp its own pod identity (service name, module pointers, stale store
    URL) onto pods it spawns — LocalBackend scrubs POD_IDENTITY_ENV from the
    inherited environ and its OWN store URL always wins."""

    def test_spawned_pod_env_never_inherits_identity(self, tmp_path,
                                                     monkeypatch):
        from kubetorch_tpu.controller import backends as be_mod
        from kubetorch_tpu.controller.backends import LocalBackend

        monkeypatch.setenv("POD_NAME", "ghost-pod-0")
        monkeypatch.setenv("KT_SERVICE_NAME", "ghost-svc")
        monkeypatch.setenv("KT_MODULE_NAME", "ghost_module")
        monkeypatch.setenv("KT_DATA_STORE_URL", "http://127.0.0.1:1")

        captured = {}

        class FakeProc:
            pid = 4242

            def poll(self):
                return None

        def fake_popen(cmd, env=None, **kw):
            captured["env"] = env
            return FakeProc()

        monkeypatch.setattr(be_mod.subprocess, "Popen", fake_popen)
        monkeypatch.setattr(be_mod, "wait_for_port",
                            lambda *a, **k: True)
        be = LocalBackend("http://127.0.0.1:9", store_url="http://127.0.0.1:2",
                          secrets_dir=str(tmp_path / "s"),
                          volumes_dir=str(tmp_path / "v"))
        be.apply("ns1", "svc1", {"kind": "Deployment",
                                 "spec": {"replicas": 1}},
                 {"KT_MODULE_NAME": "real_module"})
        env = captured["env"]
        assert env["POD_NAME"] == "svc1-0"          # its own, not the ghost's
        assert env["KT_SERVICE_NAME"] == "svc1"
        assert env["KT_MODULE_NAME"] == "real_module"   # metadata overlay
        # the backend's own store wins over anything inherited
        assert env["KT_DATA_STORE_URL"] == "http://127.0.0.1:2"


def test_compute_env_reaches_local_pods(tmp_path, monkeypatch):
    """Compute(env={...}) lands in the manifest's container env; the local
    backend must inject it like the kubelet would — previously user env
    silently worked only on real clusters."""
    from kubetorch_tpu.controller import backends as be_mod
    from kubetorch_tpu.controller.backends import LocalBackend
    from kubetorch_tpu.provisioning.manifests import (
        build_deployment_manifest, build_pod_template)

    captured = {}

    class FakeProc:
        pid = 4243

        def poll(self):
            return None

    monkeypatch.setattr(be_mod.subprocess, "Popen",
                        lambda cmd, env=None, **kw: (captured.update(env=env),
                                                     FakeProc())[1])
    monkeypatch.setattr(be_mod, "wait_for_port", lambda *a, **k: True)
    be = LocalBackend("http://127.0.0.1:9", secrets_dir=str(tmp_path / "s"),
                      volumes_dir=str(tmp_path / "v"))
    pod = build_pod_template("web", "img", {"MY_FLAG": "on"})
    be.apply("ns1", "web", build_deployment_manifest("web", "ns1", 1, pod), {})
    assert captured["env"]["MY_FLAG"] == "on"
