"""Serialization round-trips including array-bearing pytrees (SURVEY §2.3
serialization block; reference serving/http_server.py:1768-1891)."""

import numpy as np
import pytest

from kubetorch_tpu import serialization as ser
from kubetorch_tpu.exceptions import SerializationError


@pytest.mark.parametrize("fmt", [ser.JSON, ser.PICKLE, ser.MSGPACK])
def test_roundtrip_scalars(fmt):
    obj = {"a": 1, "b": [1.5, "x", None, True], "c": {"d": 2}}
    out = ser.deserialize(ser.serialize(obj, fmt), fmt, allowed=[fmt])
    assert out == obj


@pytest.mark.parametrize("fmt", [ser.JSON, ser.MSGPACK])
@pytest.mark.parametrize("dtype", ["float32", "int32", "float64", "bfloat16"])
def test_roundtrip_arrays(fmt, dtype):
    if dtype == "bfloat16":
        import ml_dtypes
        arr = np.arange(12, dtype=np.float32).reshape(3, 4).astype(ml_dtypes.bfloat16)
    else:
        arr = np.arange(12, dtype=dtype).reshape(3, 4)
    obj = {"w": arr, "nested": [arr, {"x": arr}]}
    out = ser.deserialize(ser.serialize(obj, fmt), fmt)
    np.testing.assert_array_equal(np.asarray(out["w"], dtype=np.float32),
                                  np.asarray(arr, dtype=np.float32))
    assert out["w"].dtype == arr.dtype
    assert out["nested"][1]["x"].shape == (3, 4)


def test_jax_array_roundtrip():
    import jax.numpy as jnp
    x = jnp.arange(8.0).reshape(2, 4)
    out = ser.deserialize(ser.serialize({"x": x}, ser.JSON), ser.JSON)
    np.testing.assert_array_equal(out["x"], np.asarray(x))


def test_bytes_roundtrip_json():
    obj = {"blob": b"\x00\x01binary"}
    out = ser.deserialize(ser.serialize(obj, ser.JSON), ser.JSON)
    assert out["blob"] == b"\x00\x01binary"


def test_pickle_gated_by_allowlist():
    data = ser.serialize({"x": 1}, ser.PICKLE)
    with pytest.raises(SerializationError):
        ser.deserialize(data, ser.PICKLE, allowed=ser.DEFAULT_ALLOWED)
    assert ser.deserialize(data, ser.PICKLE, allowed=["pickle"]) == {"x": 1}


def test_none_passthrough():
    assert ser.deserialize(ser.serialize(b"raw", ser.NONE), ser.NONE) == b"raw"
    assert ser.serialize(None, ser.NONE) == b""


def test_unserializable_raises():
    with pytest.raises(SerializationError):
        ser.serialize({"f": lambda: 1}, ser.JSON)
