"""Serialization round-trips including array-bearing pytrees (SURVEY §2.3
serialization block; reference serving/http_server.py:1768-1891)."""

import numpy as np
import pytest

from kubetorch_tpu import serialization as ser
from kubetorch_tpu.exceptions import SerializationError


@pytest.mark.parametrize("fmt", [ser.JSON, ser.PICKLE, ser.MSGPACK])
def test_roundtrip_scalars(fmt):
    obj = {"a": 1, "b": [1.5, "x", None, True], "c": {"d": 2}}
    out = ser.deserialize(ser.serialize(obj, fmt), fmt, allowed=[fmt])
    assert out == obj


@pytest.mark.parametrize("fmt", [ser.JSON, ser.MSGPACK])
@pytest.mark.parametrize("dtype", ["float32", "int32", "float64", "bfloat16"])
def test_roundtrip_arrays(fmt, dtype):
    if dtype == "bfloat16":
        import ml_dtypes
        arr = np.arange(12, dtype=np.float32).reshape(3, 4).astype(ml_dtypes.bfloat16)
    else:
        arr = np.arange(12, dtype=dtype).reshape(3, 4)
    obj = {"w": arr, "nested": [arr, {"x": arr}]}
    out = ser.deserialize(ser.serialize(obj, fmt), fmt)
    np.testing.assert_array_equal(np.asarray(out["w"], dtype=np.float32),
                                  np.asarray(arr, dtype=np.float32))
    assert out["w"].dtype == arr.dtype
    assert out["nested"][1]["x"].shape == (3, 4)


def test_jax_array_roundtrip():
    import jax.numpy as jnp
    x = jnp.arange(8.0).reshape(2, 4)
    out = ser.deserialize(ser.serialize({"x": x}, ser.JSON), ser.JSON)
    np.testing.assert_array_equal(out["x"], np.asarray(x))


def test_bytes_roundtrip_json():
    obj = {"blob": b"\x00\x01binary"}
    out = ser.deserialize(ser.serialize(obj, ser.JSON), ser.JSON)
    assert out["blob"] == b"\x00\x01binary"


def test_pickle_gated_by_allowlist():
    data = ser.serialize({"x": 1}, ser.PICKLE)
    with pytest.raises(SerializationError):
        ser.deserialize(data, ser.PICKLE, allowed=ser.DEFAULT_ALLOWED)
    assert ser.deserialize(data, ser.PICKLE, allowed=["pickle"]) == {"x": 1}


def test_none_passthrough():
    assert ser.deserialize(ser.serialize(b"raw", ser.NONE), ser.NONE) == b"raw"
    assert ser.serialize(None, ser.NONE) == b""


def test_unserializable_raises():
    with pytest.raises(SerializationError):
        ser.serialize({"f": lambda: 1}, ser.JSON)


@pytest.mark.parametrize("key", ["__arr__", "~__arr__", "~~__arr__",
                                 "~~~__arr__"])
def test_msgpack_sentinel_key_roundtrip(key):
    """User keys colliding with the '__arr__' typed-leaf sentinel round-trip
    at any '~'-stacking depth — escape pushes exactly one level, the decode
    hook pops exactly one (symmetric with the JSON _escape_key pair)."""
    obj = {key: [1, 2], "nested": {key: {"deeper": {key: "x"}}}}
    out = ser.deserialize(ser.serialize(obj, ser.MSGPACK), ser.MSGPACK)
    assert out == obj


def test_msgpack_sentinel_key_next_to_real_array():
    """An escaped user key and an encoder-produced array coexist in one
    dict: the array decodes, the user key unescapes."""
    import numpy as np

    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    obj = {"~__arr__": "mine", "w": arr}
    out = ser.deserialize(ser.serialize(obj, ser.MSGPACK), ser.MSGPACK)
    assert out["~__arr__"] == "mine"
    np.testing.assert_array_equal(out["w"], arr)


@pytest.mark.parametrize("key", ["__kt_array__", "~__kt_array__",
                                 "~~__kt_array__"])
def test_json_sentinel_key_roundtrip(key):
    obj = {key: 1, "nested": {key: [True]}}
    out = ser.deserialize(ser.serialize(obj, ser.JSON), ser.JSON)
    assert out == obj


def test_decoded_arrays_are_writable():
    """Preallocated-buffer decode must hand back writable arrays (the old
    frombuffer view would be read-only without the extra copy)."""
    import numpy as np

    obj = {"w": np.zeros(4, np.float32)}
    for fmt in (ser.JSON, ser.MSGPACK):
        out = ser.deserialize(ser.serialize(obj, fmt), fmt)
        out["w"][0] = 7.0
        assert out["w"][0] == 7.0


# ---------------------------------------------------------------------------
# ISSUE 10: _msgpack_escape fast path
# ---------------------------------------------------------------------------


def test_msgpack_escape_fastpath_returns_original_object():
    """A payload with no sentinel keys must come back UNTOUCHED — the
    identical object, containers not rebuilt, large bytes leaves by
    reference."""
    from kubetorch_tpu.serialization import _msgpack_escape

    big = b"\x01" * (1 << 20)
    obj = {"layers": {f"w{i}": big for i in range(8)},
           "cfg": [1, 2.5, "x", None, (3, 4)]}
    out = _msgpack_escape(obj)
    assert out is obj                       # no rebuild at all


def test_msgpack_escape_rebuild_keeps_bytes_by_reference():
    """Even when a sentinel key forces a rebuild, bytes leaves must pass
    by reference (the rebuild copies containers, never payload bytes)."""
    from kubetorch_tpu.serialization import _msgpack_escape

    big = b"\x02" * (1 << 20)
    obj = {"~__arr__": {"x": 1}, "blob": big, "nested": [big]}
    out = _msgpack_escape(obj)
    assert out is not obj                   # rebuild happened
    assert out["~~__arr__"] == {"x": 1}     # escape applied
    assert out["blob"] is big               # by reference
    assert out["nested"][0] is big


def test_msgpack_escape_fastpath_roundtrip_unchanged():
    """Wire bytes with the fast path must round-trip exactly like before:
    clean payloads, sentinel-keyed payloads, and arrays."""
    import numpy as np

    from kubetorch_tpu import serialization as ser

    payloads = [
        {"a": [1, 2, {"b": b"xy"}]},
        {"__arr__": "user-key"},            # needs escaping
        {"~__arr__": "stacked"},            # needs double-stacking
        {"w": np.arange(16, dtype=np.float32)},
    ]
    for p in payloads:
        out = ser.deserialize(ser.serialize(p, ser.MSGPACK), ser.MSGPACK)
        if "w" in p:
            np.testing.assert_array_equal(out["w"], p["w"])
        else:
            assert out == p


def test_msgpack_escape_fastpath_is_faster_than_rebuild():
    """Benchmark-backed (ISSUE 10): on a wide clean tree the scan-only
    pass must beat the unconditional rebuild — best-of-N to shrug off
    shared-CI scheduling noise."""
    import time

    from kubetorch_tpu.serialization import (_msgpack_escape,
                                             _msgpack_escape_rebuild)

    wide = {f"k{i}": [b"x" * 256, {"n": i, "m": [i, i + 1]}]
            for i in range(2000)}

    def best_of(fn, n=7):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            fn(wide)
            best = min(best, time.perf_counter() - t0)
        return best

    t_scan = best_of(_msgpack_escape)
    t_rebuild = best_of(_msgpack_escape_rebuild)
    # scan allocates nothing; rebuild reconstructs every container. The
    # 1.1 headroom keeps the assertion meaningful but unflaky.
    assert t_scan < t_rebuild * 1.1, \
        f"fast path {t_scan * 1e3:.2f}ms vs rebuild {t_rebuild * 1e3:.2f}ms"
