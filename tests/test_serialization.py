"""Serialization round-trips including array-bearing pytrees (SURVEY §2.3
serialization block; reference serving/http_server.py:1768-1891)."""

import numpy as np
import pytest

from kubetorch_tpu import serialization as ser
from kubetorch_tpu.exceptions import SerializationError


@pytest.mark.parametrize("fmt", [ser.JSON, ser.PICKLE, ser.MSGPACK])
def test_roundtrip_scalars(fmt):
    obj = {"a": 1, "b": [1.5, "x", None, True], "c": {"d": 2}}
    out = ser.deserialize(ser.serialize(obj, fmt), fmt, allowed=[fmt])
    assert out == obj


@pytest.mark.parametrize("fmt", [ser.JSON, ser.MSGPACK])
@pytest.mark.parametrize("dtype", ["float32", "int32", "float64", "bfloat16"])
def test_roundtrip_arrays(fmt, dtype):
    if dtype == "bfloat16":
        import ml_dtypes
        arr = np.arange(12, dtype=np.float32).reshape(3, 4).astype(ml_dtypes.bfloat16)
    else:
        arr = np.arange(12, dtype=dtype).reshape(3, 4)
    obj = {"w": arr, "nested": [arr, {"x": arr}]}
    out = ser.deserialize(ser.serialize(obj, fmt), fmt)
    np.testing.assert_array_equal(np.asarray(out["w"], dtype=np.float32),
                                  np.asarray(arr, dtype=np.float32))
    assert out["w"].dtype == arr.dtype
    assert out["nested"][1]["x"].shape == (3, 4)


def test_jax_array_roundtrip():
    import jax.numpy as jnp
    x = jnp.arange(8.0).reshape(2, 4)
    out = ser.deserialize(ser.serialize({"x": x}, ser.JSON), ser.JSON)
    np.testing.assert_array_equal(out["x"], np.asarray(x))


def test_bytes_roundtrip_json():
    obj = {"blob": b"\x00\x01binary"}
    out = ser.deserialize(ser.serialize(obj, ser.JSON), ser.JSON)
    assert out["blob"] == b"\x00\x01binary"


def test_pickle_gated_by_allowlist():
    data = ser.serialize({"x": 1}, ser.PICKLE)
    with pytest.raises(SerializationError):
        ser.deserialize(data, ser.PICKLE, allowed=ser.DEFAULT_ALLOWED)
    assert ser.deserialize(data, ser.PICKLE, allowed=["pickle"]) == {"x": 1}


def test_none_passthrough():
    assert ser.deserialize(ser.serialize(b"raw", ser.NONE), ser.NONE) == b"raw"
    assert ser.serialize(None, ser.NONE) == b""


def test_unserializable_raises():
    with pytest.raises(SerializationError):
        ser.serialize({"f": lambda: 1}, ser.JSON)


@pytest.mark.parametrize("key", ["__arr__", "~__arr__", "~~__arr__",
                                 "~~~__arr__"])
def test_msgpack_sentinel_key_roundtrip(key):
    """User keys colliding with the '__arr__' typed-leaf sentinel round-trip
    at any '~'-stacking depth — escape pushes exactly one level, the decode
    hook pops exactly one (symmetric with the JSON _escape_key pair)."""
    obj = {key: [1, 2], "nested": {key: {"deeper": {key: "x"}}}}
    out = ser.deserialize(ser.serialize(obj, ser.MSGPACK), ser.MSGPACK)
    assert out == obj


def test_msgpack_sentinel_key_next_to_real_array():
    """An escaped user key and an encoder-produced array coexist in one
    dict: the array decodes, the user key unescapes."""
    import numpy as np

    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    obj = {"~__arr__": "mine", "w": arr}
    out = ser.deserialize(ser.serialize(obj, ser.MSGPACK), ser.MSGPACK)
    assert out["~__arr__"] == "mine"
    np.testing.assert_array_equal(out["w"], arr)


@pytest.mark.parametrize("key", ["__kt_array__", "~__kt_array__",
                                 "~~__kt_array__"])
def test_json_sentinel_key_roundtrip(key):
    obj = {key: 1, "nested": {key: [True]}}
    out = ser.deserialize(ser.serialize(obj, ser.JSON), ser.JSON)
    assert out == obj


def test_decoded_arrays_are_writable():
    """Preallocated-buffer decode must hand back writable arrays (the old
    frombuffer view would be read-only without the extra copy)."""
    import numpy as np

    obj = {"w": np.zeros(4, np.float32)}
    for fmt in (ser.JSON, ser.MSGPACK):
        out = ser.deserialize(ser.serialize(obj, fmt), fmt)
        out["w"][0] = 7.0
        assert out["w"][0] == 7.0
