"""Continuous-batching engine (serve/engine.py).

The engine is a serving redesign of the scanned generate() path — the
non-negotiable property is EQUIVALENCE: whatever order requests are
admitted, interleaved, and retired in, each one's greedy tokens must match
a solo ``generate`` run of the same prompt. Reference analog: none (the
reference leaves batching to user handlers) — this is the beyond-parity
serving subsystem, so the contract is defined entirely by these tests.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubetorch_tpu.models.generate import generate
from kubetorch_tpu.models.llama import LlamaConfig, llama_init
from kubetorch_tpu.serve import GenerationEngine

pytestmark = [pytest.mark.level("unit"), pytest.mark.slow]


@pytest.fixture(scope="module")
def dense():
    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _reference_tokens(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):].tolist()


class TestEquivalence:
    def test_single_request_matches_generate(self, dense):
        params, cfg = dense
        prompt = [5, 17, 42, 99]
        want = _reference_tokens(params, cfg, prompt, 8)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 16))
        got = eng.submit(prompt, max_new_tokens=8)
        while eng.step():
            pass
        assert got.result(timeout=0) == want

    def test_concurrent_requests_each_match_solo_runs(self, dense):
        """Three prompts of different lengths share the grid; interleaved
        decode must not cross-contaminate slots."""
        params, cfg = dense
        prompts = [[7, 8, 9], [100, 200, 300, 400, 401], [1, 2]]
        ns = [6, 9, 4]
        want = [_reference_tokens(params, cfg, p, n)
                for p, n in zip(prompts, ns)]
        eng = GenerationEngine(params, cfg, slots=4, max_len=64,
                               prefill_buckets=(8,))
        handles = [eng.submit(p, max_new_tokens=n)
                   for p, n in zip(prompts, ns)]
        while eng.step():
            pass
        for h, w in zip(handles, want):
            assert h.result(timeout=0) == w

    def test_mid_flight_admission(self, dense):
        """A request admitted while another is mid-decode (the continuous
        part of continuous batching) still matches its solo run — and the
        early request's tokens are unchanged by the newcomer."""
        params, cfg = dense
        p1, p2 = [11, 12, 13, 14], [250, 251]
        want1 = _reference_tokens(params, cfg, p1, 10)
        want2 = _reference_tokens(params, cfg, p2, 5)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 8))
        h1 = eng.submit(p1, max_new_tokens=10)
        for _ in range(3):               # p1 decodes alone for a while
            eng.step()
        h2 = eng.submit(p2, max_new_tokens=5)
        while eng.step():
            pass
        assert h1.result(timeout=0) == want1
        assert h2.result(timeout=0) == want2

    def test_slot_reuse_after_retirement(self, dense):
        """A retired slot's stale cache rows must never leak into the next
        occupant (rows are only ever read at positions the new request has
        itself written)."""
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        pa, pb = [31, 32, 33], [77]
        wa = _reference_tokens(params, cfg, pa, 12)
        wb = _reference_tokens(params, cfg, pb, 12)
        ha = eng.submit(pa, max_new_tokens=12)
        while eng.step():
            pass
        hb = eng.submit(pb, max_new_tokens=12)   # reuses slot 0
        while eng.step():
            pass
        assert ha.result(timeout=0) == wa
        assert hb.result(timeout=0) == wb

    def test_queueing_beyond_slots(self, dense):
        """More requests than slots: the overflow waits in the queue and is
        admitted as slots free up; everyone still matches solo."""
        params, cfg = dense
        prompts = [[i + 1, i + 2] for i in range(5)]
        want = [_reference_tokens(params, cfg, p, 3) for p in prompts]
        eng = GenerationEngine(params, cfg, slots=2, max_len=32,
                               prefill_buckets=(4,))
        handles = [eng.submit(p, max_new_tokens=3) for p in prompts]
        assert eng.stats().queued == 5
        while eng.step():
            pass
        for h, w in zip(handles, want):
            assert h.result(timeout=0) == w
        s = eng.stats()
        assert s.finished_total == 5 and s.active == 0 and s.queued == 0


class TestLifecycle:
    def test_eos_retires_early(self, dense):
        params, cfg = dense
        prompt = [3, 4, 5]
        solo = _reference_tokens(params, cfg, prompt, 12)
        eos = solo[2]                     # stop at this token's 1st occurrence
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,), eos_id=eos)
        h = eng.submit(prompt, max_new_tokens=12)
        while eng.step():
            pass
        got = h.result(timeout=0)
        stop = solo.index(eos) + 1        # ends WITH the eos token
        assert got == solo[:stop] and len(got) < 12
        assert eng.stats().finished_total == 1

    def test_streaming_iteration(self, dense):
        params, cfg = dense
        prompt = [9, 10]
        want = _reference_tokens(params, cfg, prompt, 5)
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(4,))
        h = eng.submit(prompt, max_new_tokens=5)
        streamed = []
        while eng.step():
            pass
        for tok in h:
            streamed.append(tok)
        assert streamed == want
        assert h.time_to_first_token() is not None

    def test_background_thread_generate(self, dense):
        """The deployed-service surface: start() + blocking generate()."""
        params, cfg = dense
        prompt = [21, 22, 23]
        want = _reference_tokens(params, cfg, prompt, 6)
        eng = GenerationEngine(params, cfg, slots=2, max_len=32,
                               prefill_buckets=(4,)).start()
        try:
            assert eng.generate(prompt, max_new_tokens=6, timeout=120) == want
        finally:
            eng.stop()

    def test_submit_validates_length(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=16)
        with pytest.raises(ValueError, match="max_len"):
            eng.submit([1] * 10, max_new_tokens=10)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([], max_new_tokens=1)

    def test_sampled_mode_runs(self, dense):
        """Temperature>0: not bit-compared (different rng consumption than
        generate), but tokens must be in-vocab and the count exact."""
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=2, max_len=32,
                               prefill_buckets=(4,), temperature=0.8,
                               top_k=20, seed=7)
        h = eng.submit([2, 3, 4], max_new_tokens=6)
        while eng.step():
            pass
        got = h.result(timeout=0)
        assert len(got) == 6
        assert all(0 <= t < cfg.vocab_size for t in got)


class TestMoE:
    def test_moe_engine_matches_generate(self):
        from kubetorch_tpu.models.moe import MoeConfig, moe_init

        cfg = MoeConfig.tiny(dtype=jnp.float32, remat=False, attn_impl="xla")
        params = moe_init(jax.random.PRNGKey(1), cfg)
        prompt = [5, 6, 7]
        want = _reference_tokens(params, cfg, prompt, 6)
        eng = GenerationEngine(params, cfg, slots=2, max_len=32,
                               prefill_buckets=(4,))
        h = eng.submit(prompt, max_new_tokens=6)
        while eng.step():
            pass
        assert h.result(timeout=0) == want


class TestHandleRetry:
    def test_result_timeout_keeps_drained_tokens(self, dense):
        """A result() that times out mid-decode must not eat the tokens it
        already drained — a retry sees the full stream from the start."""
        params, cfg = dense
        prompt = [5, 17, 42, 99]
        want = _reference_tokens(params, cfg, prompt, 8)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        h = eng.submit(prompt, max_new_tokens=8)
        for _ in range(3):              # partial decode only
            eng.step()
        with pytest.raises(TimeoutError):
            h.result(timeout=0.01)
        while eng.step():
            pass
        assert h.result(timeout=0) == want       # nothing lost
        assert h.result(timeout=0) == want       # idempotent after done
        assert list(h) == want                   # iteration agrees too

    def test_max_new_tokens_validated(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=16)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2], max_new_tokens=0)

    def test_start_is_idempotent_single_loop(self, dense):
        import threading

        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=16)
        try:
            threads = [threading.Thread(target=eng.start) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            alive = [t for t in threading.enumerate()
                     if t.name == "kt-gen-engine"]
            assert len(alive) == 1
        finally:
            eng.stop()


@pytest.mark.level("release")
class TestShardedServing:
    def test_engine_matches_under_tensor_sharded_mesh(self, cpu_mesh_devices):
        """Multi-chip serving is the training sharding story: the same
        engine jits run GSPMD-partitioned when params carry NamedShardings
        on a data×tensor mesh — and the greedy tokens are unchanged."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import LLAMA_RULES, shard_pytree

        params, cfg = (llama_init(jax.random.PRNGKey(0),
                                  LlamaConfig.tiny(attn_impl="xla",
                                                   dtype=jnp.float32,
                                                   remat=False)),
                       LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                                        remat=False))
        prompts = [[5, 17, 42], [9, 9, 9, 9]]
        want = [_reference_tokens(params, cfg, p, 6) for p in prompts]

        mesh = build_mesh({"data": 2, "tensor": 2}, devices=cpu_mesh_devices[:4])
        sharded = shard_pytree(params, LLAMA_RULES, mesh)
        with use_mesh(mesh):
            eng = GenerationEngine(sharded, cfg, slots=4, max_len=32,
                                   prefill_buckets=(4,))
            handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
            while eng.step():
                pass
        for h, w in zip(handles, want):
            assert h.result(timeout=0) == w


class TestPerRequestSampling:
    def test_greedy_and_sampled_share_the_grid(self, dense):
        """A greedy request decoding next to a sampled one must produce its
        exact solo-run tokens — per-slot temperatures ride one compiled
        step, never a recompile or cross-slot contamination."""
        params, cfg = dense
        prompt_g = [5, 17, 42, 99]
        want = _reference_tokens(params, cfg, prompt_g, 8)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,), temperature=0.9, seed=3)
        hg = eng.submit(prompt_g, max_new_tokens=8, temperature=0.0)
        hs = eng.submit([7, 7], max_new_tokens=8)        # engine default 0.9
        while eng.step():
            pass
        assert hg.result(timeout=0) == want
        sampled = hs.result(timeout=0)
        assert len(sampled) == 8
        assert all(0 <= t < cfg.vocab_size for t in sampled)


class TestPrefixCache:
    def test_prefix_cached_matches_full_prompt(self, dense):
        """submit(suffix, prefix_id) must equal a solo generate of
        prefix+suffix — the cached K/V plus positional offsets reproduce
        the from-zero prefill exactly (dense)."""
        params, cfg = dense
        prefix = [11, 12, 13, 14, 15]
        suffixes = [[21, 22], [31, 32, 33]]
        want = [_reference_tokens(params, cfg, prefix + s, 6)
                for s in suffixes]
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 8))
        pid = eng.register_prefix(prefix)
        handles = [eng.submit(s, max_new_tokens=6, prefix_id=pid)
                   for s in suffixes]
        while eng.step():
            pass
        for h, w in zip(handles, want):
            assert h.result(timeout=0) == w

    def test_prefix_and_plain_requests_interleave(self, dense):
        params, cfg = dense
        prefix = [50, 51, 52]
        plain = [1, 2, 3]
        want_pref = _reference_tokens(params, cfg, prefix + [60], 5)
        want_plain = _reference_tokens(params, cfg, plain, 5)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,))
        pid = eng.register_prefix(prefix)
        h1 = eng.submit([60], max_new_tokens=5, prefix_id=pid)
        h2 = eng.submit(plain, max_new_tokens=5)
        while eng.step():
            pass
        assert h1.result(timeout=0) == want_pref
        assert h2.result(timeout=0) == want_plain

    def test_prefix_validation(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=16,
                               prefill_buckets=(4,))
        with pytest.raises(KeyError):
            eng.submit([1], max_new_tokens=1, prefix_id=99)
        pid = eng.register_prefix([1, 2, 3, 4])
        with pytest.raises(ValueError, match="max_len"):
            eng.submit([1] * 8, max_new_tokens=8, prefix_id=pid)


class TestPrefixLifecycle:
    def test_unregister_frees_and_queued_request_fails_cleanly(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        pid = eng.register_prefix([1, 2, 3])
        h_ok = eng.submit([4], max_new_tokens=3, prefix_id=pid)
        eng.step()         # admits h_ok into the single slot
        # queue a second against the same prefix, then unregister BEFORE it
        # can be admitted (the slot is busy with h_ok)
        h_fail = eng.submit([5], max_new_tokens=3, prefix_id=pid)
        assert eng.unregister_prefix(pid) is True
        assert eng.unregister_prefix(pid) is False
        while eng.step():
            pass
        assert len(h_ok.result(timeout=0)) == 3   # admitted before removal
        with pytest.raises(KeyError):
            h_fail.result(timeout=0)
        # the loop survived: new plain requests still serve
        h_next = eng.submit([6, 7], max_new_tokens=2)
        while eng.step():
            pass
        assert len(h_next.result(timeout=0)) == 2


def test_prefix_in_oversized_bucket_config(dense):
    """A short prefix must not eat a whole oversized bucket's worth of the
    max_len budget: when the smallest bucket leaves no room for suffix +
    generation, the stored K/V trims to the exact prefix length."""
    params, cfg = dense
    eng = GenerationEngine(params, cfg, slots=1, max_len=16,
                           prefill_buckets=(16,))   # only bucket == max_len
    prefix = [11, 12, 13]
    want = _reference_tokens(params, cfg, prefix + [60], 4)
    pid = eng.register_prefix(prefix)
    assert eng._prefixes[pid][0].shape[2] == 3      # trimmed, not 16
    h = eng.submit([60], max_new_tokens=4, prefix_id=pid)
    while eng.step():
        pass
    assert h.result(timeout=0) == want


# ---------------------------------------------------------------------------
# multi-LoRA serving
# ---------------------------------------------------------------------------


def _rand_adapters(seed, params, lcfg, scale=0.05):
    """Non-trivial adapters: lora_init's B factors are zeros (identity), so
    randomize them — each seed is a distinct adapter."""
    from kubetorch_tpu.models.lora import lora_init
    adap = lora_init(jax.random.PRNGKey(seed), params, lcfg)
    keys = jax.random.split(jax.random.PRNGKey(seed + 1000),
                            len(adap["layers"]))
    adap["layers"] = {
        k: (v if k.endswith("__a")
            else jax.random.normal(kk, v.shape, v.dtype) * scale)
        for kk, (k, v) in zip(keys, sorted(adap["layers"].items()))}
    return adap


class TestMultiLora:
    """Unmerged activation-path adapters: different slots run different
    adapters through ONE compiled decode step. The contract mirrors
    TestEquivalence — a slot's tokens must be bit-identical to the same
    request run alone on an identically-configured engine."""

    @pytest.fixture(scope="class")
    def bank(self, dense):
        from kubetorch_tpu.models.lora import LoraConfig
        params, cfg = dense
        lcfg = LoraConfig(rank=4)
        return lcfg, _rand_adapters(7, params, lcfg), _rand_adapters(8, params, lcfg)

    def _engine(self, dense, bank):
        params, cfg = dense
        lcfg, ad_a, ad_b = bank
        eng = GenerationEngine(params, cfg, slots=4, max_len=64,
                               prefill_buckets=(8,))
        ida = eng.register_adapter(ad_a, lcfg)
        idb = eng.register_adapter(ad_b, lcfg)
        return eng, ida, idb

    def test_slot_isolation(self, dense, bank):
        """Adapter-A request beside an adapter-B neighbor == the same
        A request alone on a fresh engine with identical banks."""
        pa, na = [5, 17, 42], 6
        pb, nb = [9, 9, 2, 30], 8
        solo = {}
        for which in ("a", "b"):
            eng, ida, idb = self._engine(dense, bank)
            h = (eng.submit(pa, max_new_tokens=na, adapter_id=ida)
                 if which == "a"
                 else eng.submit(pb, max_new_tokens=nb, adapter_id=idb))
            while eng.step():
                pass
            solo[which] = h.result(timeout=0)
        eng, ida, idb = self._engine(dense, bank)
        ha = eng.submit(pa, max_new_tokens=na, adapter_id=ida)
        hb = eng.submit(pb, max_new_tokens=nb, adapter_id=idb)
        while eng.step():
            pass
        assert ha.result(timeout=0) == solo["a"]
        assert hb.result(timeout=0) == solo["b"]
        # the adapters genuinely differ (A's tokens aren't B's on a shared
        # prompt would be a weaker check; assert the deltas did something)
        base = GenerationEngine(dense[0], dense[1], slots=4, max_len=64,
                                prefill_buckets=(8,))
        hbase = base.submit(pa, max_new_tokens=na)
        while base.step():
            pass
        assert hbase.result(timeout=0) != solo["a"]

    def test_adapter_beside_base_traffic(self, dense, bank):
        """A no-adapter request on an engine WITH banks (bank index 0 = the
        zero adapter) is bit-identical to the plain engine: the gathered
        zero factors contribute exactly 0.0."""
        params, cfg = dense
        prompt, n = [7, 8, 9], 6
        want = _reference_tokens(params, cfg, prompt, n)
        eng, ida, _ = self._engine(dense, bank)
        h_base = eng.submit(prompt, max_new_tokens=n)
        h_lora = eng.submit([4, 4], max_new_tokens=5, adapter_id=ida)
        while eng.step():
            pass
        assert h_base.result(timeout=0) == want
        assert len(h_lora.result(timeout=0)) == 5

    def test_activation_path_matches_merged(self, dense, bank):
        """The unmerged x·W + s·(x·A)·B path must agree with serving
        merge_lora(base, A) weights — the oracle the adapters train
        against."""
        from kubetorch_tpu.models.lora import merge_lora
        params, cfg = dense
        lcfg, ad_a, _ = bank
        prompt, n = [5, 17, 42, 99], 8
        merged = merge_lora(params, ad_a, lcfg)
        want = _reference_tokens(merged, cfg, prompt, n)
        eng, ida, _ = self._engine(dense, bank)
        h = eng.submit(prompt, max_new_tokens=n, adapter_id=ida)
        while eng.step():
            pass
        assert h.result(timeout=0) == want

    def test_prefix_with_adapter(self, dense, bank):
        """A prefix computed through adapter A + suffix/decode through A ==
        the full prompt through A."""
        params, cfg = dense
        lcfg, ad_a, _ = bank
        prefix, suffix, n = [11, 12, 13, 14], [60, 61], 5
        eng, ida, _ = self._engine(dense, bank)
        h_full = eng.submit(prefix + suffix, max_new_tokens=n, adapter_id=ida)
        while eng.step():
            pass
        want = h_full.result(timeout=0)
        eng2, ida2, _ = self._engine(dense, bank)
        pid = eng2.register_prefix(prefix, adapter_id=ida2)
        h = eng2.submit(suffix, max_new_tokens=n, prefix_id=pid,
                        adapter_id=ida2)
        while eng2.step():
            pass
        assert h.result(timeout=0) == want

    def test_unregister_reuses_slot_and_fails_queued(self, dense, bank):
        params, cfg = dense
        lcfg, ad_a, ad_b = bank
        eng, ida, idb = self._engine(dense, bank)
        n_bank = eng._banks["wq"][0].shape[1]
        assert eng.unregister_adapter(idb) is True
        assert eng.unregister_adapter(idb) is False
        # freed slot is reused: no bank growth
        idc = eng.register_adapter(ad_b, lcfg)
        assert eng._banks["wq"][0].shape[1] == n_bank
        # a submit against the evicted id fails fast...
        with pytest.raises(KeyError):
            eng.submit([1, 2], max_new_tokens=2, adapter_id=idb)
        # ...and one already queued fails cleanly through its handle
        h = eng.submit([1, 2], max_new_tokens=2, adapter_id=idc)
        eng.unregister_adapter(idc)
        while eng.step():
            pass
        with pytest.raises(KeyError):
            h.result(timeout=0)
        # the loop survived
        h2 = eng.submit([3], max_new_tokens=2, adapter_id=ida)
        while eng.step():
            pass
        assert len(h2.result(timeout=0)) == 2

    def test_config_mismatch_rejected(self, dense, bank):
        from kubetorch_tpu.models.lora import LoraConfig
        params, cfg = dense
        lcfg, ad_a, _ = bank
        eng, _, _ = self._engine(dense, bank)
        bad = _rand_adapters(9, params, LoraConfig(rank=2))
        with pytest.raises(ValueError, match="rank|config"):
            eng.register_adapter(bad, LoraConfig(rank=2))

    def test_late_registration_grows_bank(self, dense, bank):
        """Registering after traffic ran (bank growth → one recompile)
        still serves both old and new adapters correctly."""
        params, cfg = dense
        lcfg, ad_a, ad_b = bank
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(8,))
        ida = eng.register_adapter(ad_a, lcfg)
        h = eng.submit([5, 17, 42], max_new_tokens=4, adapter_id=ida)
        while eng.step():
            pass
        first = h.result(timeout=0)
        idb = eng.register_adapter(ad_b, lcfg)      # grows the bank
        h2 = eng.submit([5, 17, 42], max_new_tokens=4, adapter_id=ida)
        while eng.step():
            pass
        assert h2.result(timeout=0) == first        # A unchanged by growth

    def test_non_attention_targets_rejected(self, dense, bank):
        """Training/merging adapt any leaf; the activation path serves only
        the attention projections — banking w_gate would silently drop it."""
        from kubetorch_tpu.models.lora import LoraConfig
        params, cfg = dense
        lcfg = LoraConfig(rank=4, targets=("wq", "w_gate"))
        bad = _rand_adapters(11, params, lcfg)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(8,))
        with pytest.raises(ValueError, match="merge_lora"):
            eng.register_adapter(bad, lcfg)

    def test_unregister_repoints_inflight_to_base(self, dense, bank):
        """Evicting an adapter mid-decode must repoint its slots at bank
        index 0 (base model) — slot reuse by a new tenant must never leak
        into the old request's remaining tokens."""
        params, cfg = dense
        lcfg, ad_a, ad_b = bank
        eng, ida, idb = self._engine(dense, bank)
        h = eng.submit([5, 17, 42], max_new_tokens=6, adapter_id=ida)
        eng.step()                                   # admit + first decode
        slot = next(i for i, r in enumerate(eng._slot_req) if r is not None)
        assert eng._aidx[slot] == eng._adapter_slots[ida]
        eng.unregister_adapter(ida)
        assert eng._aidx[slot] == 0                  # base fallback
        idc = eng.register_adapter(ad_b, lcfg)       # reuses the freed index
        while eng.step():
            pass
        assert len(h.result(timeout=0)) == 6         # drained, no crash

    def test_eviction_during_prefill_falls_back_to_base(self, dense, bank,
                                                        monkeypatch):
        """The adapter can be evicted (and its bank index reused by a new
        tenant) in the window between admission resolving the index and
        the prefill finishing — the slot must then point at base (0),
        never at the reusing tenant's factors."""
        import kubetorch_tpu.serve.engine as eng_mod
        params, cfg = dense
        lcfg, ad_a, ad_b = bank
        eng, ida, idb = self._engine(dense, bank)
        orig = eng_mod._prefill
        hit = {}

        def racy_prefill(*a, **kw):
            out = orig(*a, **kw)
            if "adapter" in kw and not hit:   # only the adapter prefill
                hit["idx"] = eng._adapter_slots[ida]
                eng.unregister_adapter(ida)
                hit["reused"] = eng.register_adapter(ad_b, lcfg)
            return out

        monkeypatch.setattr(eng_mod, "_prefill", racy_prefill)
        h = eng.submit([5, 17, 42], max_new_tokens=4, adapter_id=ida)
        eng.step()
        slot = next(i for i, r in enumerate(eng._slot_req) if r is not None)
        assert hit and eng._adapter_slots[hit["reused"]] == hit["idx"]
        assert eng._aidx[slot] == 0            # base, not the new tenant
        while eng.step():
            pass
        assert len(h.result(timeout=0)) == 4


class TestContextShardedServing:
    """Long-context serving: the cache's sequence axis sharded over the
    ``context`` mesh axis, decode via local attention + one online-softmax
    combine (parallel/ring_attention.sp_decode_attention) — no chip ever
    holds more than 1/C of the cache."""

    def test_sp_decode_op_matches_einsum(self, cpu_mesh_devices):
        """Direct op check: sharded decode == the unsharded masked-einsum
        reference, across frontier positions including shard boundaries."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.ring_attention import (
            sp_decode_attention_sharded)

        b, nh, nkv, hd, s = 4, 4, 2, 32, 64
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (b, nh, hd), jnp.float32)
        ck = jax.random.normal(jax.random.PRNGKey(1), (b, s, nkv, hd),
                               jnp.float32)
        cv = jax.random.normal(jax.random.PRNGKey(2), (b, s, nkv, hd),
                               jnp.float32)
        # frontiers: inside shard 0, exactly at a shard boundary, deep in
        # the last shard, and row 0
        pos = jnp.array([5, 15, 63, 0], jnp.int32)
        mesh = build_mesh({"data": 2, "context": 4},
                          devices=cpu_mesh_devices[:8])
        got = jax.jit(lambda *a: sp_decode_attention_sharded(
            *a, mesh, scale=hd ** -0.5))(q, ck, cv, pos)

        group = nh // nkv
        qg = q.reshape(b, nkv, group, hd)
        logits = (jnp.einsum("bkgh,bskh->bkgs", qg, ck)
                  .astype(jnp.float32) * (hd ** -0.5))
        mask = jnp.arange(s)[None, :] <= pos[:, None]
        logits = jnp.where(mask[:, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(cv.dtype)
        want = jnp.einsum("bkgs,bskh->bkgh", probs, cv).reshape(b, nh, hd)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_engine_matches_under_context_sharded_mesh(self,
                                                      cpu_mesh_devices):
        """The engine on a data×context mesh emits the same greedy tokens
        as the single-device run — the serving-side long-context story."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import LLAMA_RULES, shard_pytree

        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        prompts = [[5, 17, 42], [9, 9, 9, 9]]
        want = [_reference_tokens(params, cfg, p, 6) for p in prompts]

        mesh = build_mesh({"data": 2, "context": 4},
                          devices=cpu_mesh_devices[:8])
        sharded = shard_pytree(params, LLAMA_RULES, mesh)
        with use_mesh(mesh):
            eng = GenerationEngine(sharded, cfg, slots=4, max_len=32,
                                   prefill_buckets=(4,))
            handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
            while eng.step():
                pass
        for h, w in zip(handles, want):
            assert h.result(timeout=0) == w

    def test_background_loop_keeps_context_sharding(self, cpu_mesh_devices):
        """The ambient mesh is THREAD-LOCAL: an engine built under
        use_mesh but driven by its background loop thread (start()/
        generate() — the kt.cls deployment mode) must still trace the
        context-sharded decode path, not silently fall back."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import LLAMA_RULES, shard_pytree

        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        want = _reference_tokens(params, cfg, [5, 17, 42], 6)
        mesh = build_mesh({"data": 2, "context": 4},
                          devices=cpu_mesh_devices[:8])
        sharded = shard_pytree(params, LLAMA_RULES, mesh)
        with use_mesh(mesh):
            eng = GenerationEngine(sharded, cfg, slots=2, max_len=32,
                                   prefill_buckets=(4,))
        # OUTSIDE the mesh context, on the loop thread:
        eng.start()
        try:
            got = eng.generate([5, 17, 42], 6)
        finally:
            eng.stop()
        assert got == want
        spec = str(eng._cache.k.sharding.spec)
        assert "context" in spec, spec
        # really 1/8 of the grid per chip
        leaf = eng._cache.k
        assert leaf.addressable_shards[0].data.nbytes * 8 == leaf.nbytes

    def test_non_dividing_shapes_fall_back_densely(self, cpu_mesh_devices):
        """max_len not divisible by the context axis: the sp path must
        step aside (shard_map cannot pad) and serving stays exact through
        the dense path."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import LLAMA_RULES, shard_pytree

        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        want = _reference_tokens(params, cfg, [5, 17, 42], 6)
        mesh = build_mesh({"data": 2, "context": 4},
                          devices=cpu_mesh_devices[:8])
        sharded = shard_pytree(params, LLAMA_RULES, mesh)
        with use_mesh(mesh):
            eng = GenerationEngine(sharded, cfg, slots=2, max_len=30,
                                   prefill_buckets=(4,))   # 30 % 4 != 0
            h = eng.submit([5, 17, 42], max_new_tokens=6)
            while eng.step():
                pass
        assert h.result(timeout=0) == want

    def test_quantized_context_sharded(self, cpu_mesh_devices):
        """int8 KV cache × context sharding compose: the quant sp combine
        serves exactly what the single-device quant engine serves."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import LLAMA_RULES, shard_pytree

        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        solo = GenerationEngine(params, cfg, slots=2, max_len=32,
                                prefill_buckets=(4,), quantize_kv=True)
        hs = solo.submit([5, 17, 42], max_new_tokens=6)
        while solo.step():
            pass
        want = hs.result(timeout=0)

        mesh = build_mesh({"data": 2, "context": 4},
                          devices=cpu_mesh_devices[:8])
        sharded = shard_pytree(params, LLAMA_RULES, mesh)
        with use_mesh(mesh):
            eng = GenerationEngine(sharded, cfg, slots=2, max_len=32,
                                   prefill_buckets=(4,), quantize_kv=True)
            h = eng.submit([5, 17, 42], max_new_tokens=6)
            while eng.step():
                pass
        assert h.result(timeout=0) == want
        assert "context" in str(eng._cache.kq.sharding.spec)

    def test_long_prompt_ring_prefill(self, cpu_mesh_devices):
        """Prompts at/above RING_PREFILL_MIN_T prefill via sequence-sharded
        ring attention on a context mesh — no chip holds the full (T, T)
        attention problem — and serving stays exact vs the single-device
        engine. An explicit attn_impl="xla" is a single-chip choice the
        gate must honor."""
        from kubetorch_tpu.models import generate as gen_mod
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import LLAMA_RULES, shard_pytree

        cfg = LlamaConfig.tiny(attn_impl="auto", dtype=jnp.float32,
                               remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        prompt = [int(x) for x in
                  np.random.RandomState(3).randint(
                      1, cfg.vocab_size, gen_mod.RING_PREFILL_MIN_T)]

        solo = GenerationEngine(params, cfg, slots=1, max_len=520,
                                prefill_buckets=(512,))
        h = solo.submit(prompt, max_new_tokens=6)
        while solo.step():
            pass
        want = h.result(timeout=0)

        mesh = build_mesh({"data": 2, "context": 4},
                          devices=cpu_mesh_devices[:8])
        sharded = shard_pytree(params, LLAMA_RULES, mesh)
        # spy at TRACE time: the ring path must actually engage, not
        # silently fall back to the dense prefill
        import kubetorch_tpu.parallel.ring_attention as ring_mod
        traced = {}
        orig = ring_mod.ring_attention_sharded

        def spy(*a, **kw):
            traced["ring"] = True
            return orig(*a, **kw)

        ring_mod.ring_attention_sharded = spy
        try:
            with use_mesh(mesh):
                eng = GenerationEngine(sharded, cfg, slots=1, max_len=520,
                                       prefill_buckets=(512,))
                h = eng.submit(prompt, max_new_tokens=6)
                while eng.step():
                    pass
        finally:
            ring_mod.ring_attention_sharded = orig
        assert traced.get("ring"), "ring prefill never traced"
        assert h.result(timeout=0) == want
        # explicit "xla" opts OUT of the sequence-sharded prefill
        xcfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                                remat=False)
        assert gen_mod._sp_prefill_impl(xcfg, 1, 512) is None


def test_engine_kt_metrics_hook(dense):
    """The engine's __kt_metrics__ gauges: numeric, complete, and live —
    what a deployed engine exports through the pod scrape."""
    params, cfg = dense
    eng = GenerationEngine(params, cfg, slots=2, max_len=32,
                           prefill_buckets=(4,))
    h = eng.submit([1, 2], max_new_tokens=3)
    while eng.step():
        pass
    m = eng.__kt_metrics__()
    assert all(isinstance(v, float) for v in m.values())
    assert m["engine_finished_total"] == 1.0
    assert m["engine_tokens_generated"] == 3.0
    assert m["engine_slots"] == 2.0
    # speculative engines add acceptance gauges
    from kubetorch_tpu.serve import SpeculativeEngine
    dcfg = LlamaConfig.tiny(dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
                            ffn_dim=64, attn_impl="xla", dtype=jnp.float32,
                            remat=False)
    draft = llama_init(jax.random.PRNGKey(7), dcfg)
    spec = SpeculativeEngine(params, cfg, draft, dcfg, spec_k=2, slots=2,
                             max_len=32, prefill_buckets=(4,))
    h = spec.submit([1, 2], max_new_tokens=3)
    while spec.step():
        pass
    sm = spec.__kt_metrics__()
    assert "engine_spec_acceptance_rate" in sm
    assert sm["engine_spec_rounds"] >= 1.0
    assert h.result(timeout=0) is not None


class TestCancellation:
    def test_cancel_queued_never_admits(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(4,))
        h1 = eng.submit([1, 2], max_new_tokens=8)
        h2 = eng.submit([3, 4], max_new_tokens=8)      # queued behind h1
        assert h2.cancel() is True
        assert h2.cancel() is False                    # idempotent
        while eng.step():
            pass
        assert len(h1.result(timeout=0)) == 8
        assert h2.result(timeout=0) == []              # clean empty stream
        assert eng.stats().admitted_total == 1

    def test_cancel_active_frees_slot_mid_stream(self, dense):
        """An active request stops at the next step boundary, keeps its
        partial tokens, and its slot serves the next caller exactly."""
        params, cfg = dense
        want_next = _reference_tokens(params, cfg, [9, 8], 5)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        h = eng.submit([1, 2, 3], max_new_tokens=30)
        for _ in range(3):
            eng.step()
        assert h.cancel() is True
        while eng.step():
            pass
        got = h.result(timeout=0)
        assert 1 <= len(got) < 30                      # partial stream
        s = eng.stats()
        assert s.active == 0 and s.finished_total == 1
        # the freed slot serves the next request bit-exactly
        h2 = eng.submit([9, 8], max_new_tokens=5)
        while eng.step():
            pass
        assert h2.result(timeout=0) == want_next

    def test_cancel_unknown_or_finished_is_noop(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(4,))
        h = eng.submit([1, 2], max_new_tokens=2)
        while eng.step():
            pass
        assert len(h.result(timeout=0)) == 2
        assert h.cancel() is False                     # already finished
        assert eng.cancel(99999) is False              # unknown id

    def test_cancel_speculative_slot(self, dense):
        """Cancellation frees a SPECULATIVE slot's ledgers too — the next
        occupant must not inherit pending tokens or a stale frontier."""
        from kubetorch_tpu.serve import SpeculativeEngine
        params, cfg = dense
        dcfg = LlamaConfig.tiny(dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
                                ffn_dim=64, attn_impl="xla",
                                dtype=jnp.float32, remat=False)
        draft = llama_init(jax.random.PRNGKey(7), dcfg)
        eng = SpeculativeEngine(params, cfg, draft, dcfg, spec_k=2,
                                slots=1, max_len=64, prefill_buckets=(4,))
        want = _reference_tokens(params, cfg, [9, 8], 5)
        h = eng.submit([1, 2, 3], max_new_tokens=30)
        eng.step()
        assert h.cancel() is True
        while eng.step():
            pass
        assert eng._slot_pending[0] == [] and eng._spec_valid[0] == 0
        h2 = eng.submit([9, 8], max_new_tokens=5)
        while eng.step():
            pass
        assert h2.result(timeout=0) == want

    def test_cancel_mid_admission_window(self, dense, monkeypatch):
        """A cancel landing while _admit_one's prefill runs (popped from
        the queue, slot not yet assigned) must take effect — the first
        compile can last seconds and disconnects love that window."""
        import kubetorch_tpu.serve.engine as eng_mod
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        orig = eng_mod._prefill
        hit = {}

        def racy_prefill(*a, **kw):
            out = orig(*a, **kw)
            if "cancelled" not in hit:      # cancel DURING the admission
                hit["cancelled"] = eng.cancel(h.request_id)
            return out

        monkeypatch.setattr(eng_mod, "_prefill", racy_prefill)
        h = eng.submit([1, 2, 3], max_new_tokens=30)
        while eng.step():
            pass
        assert hit["cancelled"] is True
        got = h.result(timeout=0)
        assert len(got) < 30                 # never decoded its budget
        assert eng.stats().active == 0

    def test_double_cancel_active_reads_false(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(4,))
        h = eng.submit([1, 2], max_new_tokens=10)
        eng.step()
        assert h.cancel() is True
        assert h.cancel() is False           # same contract as queued path
        while eng.step():
            pass


class TestDecodeBlock:
    """K decode steps per dispatch (``decode_block``): the host pays one
    dispatch per K tokens while admission/retirement stay host-side at
    block boundaries. The contract is bit-equivalence with the one-step
    engine for everything deterministic — mid-block retirement (budget,
    eos, stop sequences), penalties, int8 KV — since greedy decode is
    RNG-independent and the block scan runs the same per-step math."""

    def _run(self, eng, submits):
        handles = [eng.submit(*a, **k) for a, k in submits]
        while eng.step():
            pass
        return [h.result(timeout=0) for h in handles]

    def test_block_matches_oracle_mid_block_retirement(self, dense):
        """Budgets 3/8/5 against block=4: slots retire mid-block (the
        garbage tail past each stop point must be discarded) and every
        stream still matches its solo generate run."""
        params, cfg = dense
        prompts = [[7, 8, 9], [100, 200, 300, 400, 401], [1, 2]]
        ns = [3, 8, 5]
        want = [_reference_tokens(params, cfg, p, n)
                for p, n in zip(prompts, ns)]
        eng = GenerationEngine(params, cfg, slots=4, max_len=64,
                               prefill_buckets=(8,), decode_block=4)
        got = self._run(eng, [((p,), {"max_new_tokens": n})
                              for p, n in zip(prompts, ns)])
        assert got == want
        # 8 tokens of budget after the prefill token = 7 needed decodes;
        # every dispatch runs the FULL block (no tail-sized recompiles),
        # so the engine pays two 4-step blocks and discards the overshoot
        assert eng.stats().decode_steps == 8

    def test_block_eos_and_stop_sequences(self, dense):
        """eos and stop-sequence retirement land mid-block; the emitted
        streams end exactly where the one-step engine's do."""
        params, cfg = dense
        prompt = [3, 4, 5]
        solo = _reference_tokens(params, cfg, prompt, 12)
        eos = solo[2]
        stop_seq = solo[1:3]              # retires at token 3 of the solo run
        for kwargs, want in (
                ({"eos_id": eos}, solo[:solo.index(eos) + 1]),
                ({}, None),               # stop= goes on the request below
        ):
            eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                                   prefill_buckets=(4,), decode_block=8,
                                   **kwargs)
            sub_kw = {"max_new_tokens": 12}
            if not kwargs:
                sub_kw["stop"] = [stop_seq]
                want = solo[:3]
            got = self._run(eng, [((prompt,), sub_kw)])[0]
            assert got == want and len(got) < 12

    def test_block_penalties_match_one_step(self, dense):
        """Greedy + repetition penalties are deterministic: the block
        engine's counts ledger (carried through the scan) must steer
        exactly like the one-step engine's."""
        params, cfg = dense
        prompt = [5, 17, 42, 99]
        runs = []
        for block in (1, 4):
            eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                                   prefill_buckets=(4,), decode_block=block)
            runs.append(self._run(eng, [
                ((prompt,), {"max_new_tokens": 10,
                             "frequency_penalty": 0.8}),
                (([1, 2],), {"max_new_tokens": 6,
                             "presence_penalty": 1.1}),
            ]))
        assert runs[0] == runs[1]
        # the penalties actually bit: the penalized stream differs from the
        # unpenalized oracle
        assert runs[0][0] != _reference_tokens(params, cfg, prompt, 10)

    def test_block_quantized_kv_matches_one_step(self, dense):
        params, cfg = dense
        prompts = [[7, 8, 9], [1, 2]]
        runs = []
        for block in (1, 4):
            eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                                   prefill_buckets=(4,), decode_block=block,
                                   quantize_kv=True)
            runs.append(self._run(eng, [((p,), {"max_new_tokens": 7})
                                        for p in prompts]))
        assert runs[0] == runs[1]

    def test_spec_engine_refuses_decode_block(self, dense):
        params, cfg = dense
        from kubetorch_tpu.serve.spec_engine import SpeculativeEngine
        with pytest.raises(ValueError, match="decode_block"):
            SpeculativeEngine(params, cfg, params, cfg, decode_block=4)


class TestAutoPrefix:
    """auto_prefix=True: submit() reuses the longest registered prefix the
    prompt starts with — full prompt in, cached K/V spliced, exact same
    tokens out as a from-zero prefill of the whole prompt."""

    def test_longest_match_reused_and_exact(self, dense):
        params, cfg = dense
        short = [5, 17]
        long = [5, 17, 42, 7]
        tail = [9, 11]
        want = _reference_tokens(params, cfg, long + tail, 6)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 8), auto_prefix=True)
        eng.register_prefix(short)
        pid_long = eng.register_prefix(long)
        h = eng.submit(long + tail, max_new_tokens=6)
        while eng.step():
            pass
        assert h.result(timeout=0) == want
        assert eng._prefix_hits == 1
        # the LONGEST prefix was the one matched: its bucket (4) + suffix
        # rows landed, which the slot frontier position reflects — and a
        # prompt that extends only the short prefix still matches short
        h2 = eng.submit([5, 17, 200], max_new_tokens=4)
        while eng.step():
            pass
        want2 = _reference_tokens(params, cfg, [5, 17, 200], 4)
        assert h2.result(timeout=0) == want2
        assert eng._prefix_hits == 2
        assert eng.unregister_prefix(pid_long)

    def test_no_match_and_exact_equal_prompt_fall_back(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,), auto_prefix=True)
        eng.register_prefix([5, 17, 42])
        # prompt EQUAL to the prefix leaves no suffix to prefill → full
        # prefill path, not a degenerate zero-length suffix
        want = _reference_tokens(params, cfg, [5, 17, 42], 4)
        h = eng.submit([5, 17, 42], max_new_tokens=4)
        # unrelated prompt → no match
        want2 = _reference_tokens(params, cfg, [9, 9], 3)
        h2 = eng.submit([9, 9], max_new_tokens=3)
        while eng.step():
            pass
        assert h.result(timeout=0) == want
        assert h2.result(timeout=0) == want2
        assert eng._prefix_hits == 0

    def test_adapter_mismatch_not_matched(self, dense):
        """A prefix cached through adapter A must not serve base traffic:
        the auto-match is adapter-keyed."""
        from kubetorch_tpu.models.lora import LoraConfig
        params, cfg = dense
        lcfg = LoraConfig(rank=2, targets=("wq",))
        ad = _rand_adapters(7, params, lcfg)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 8), auto_prefix=True)
        aid = eng.register_adapter(ad, lcfg)
        eng.register_prefix([5, 17, 42], adapter_id=aid)
        want = _reference_tokens(params, cfg, [5, 17, 42, 9], 4)
        h = eng.submit([5, 17, 42, 9], max_new_tokens=4)   # base traffic
        while eng.step():
            pass
        assert h.result(timeout=0) == want
        assert eng._prefix_hits == 0                       # no cross-use
        # but a request ON adapter A does match it
        ha = eng.submit([5, 17, 42, 9], max_new_tokens=4, adapter_id=aid)
        while eng.step():
            pass
        assert eng._prefix_hits == 1
        assert len(ha.result(timeout=0)) == 4

    def test_eviction_between_submit_and_admission_falls_back(self, dense):
        """An auto-matched prefix evicted while the request is queued must
        not fail the request — admission restores the full prompt."""
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4, 8), auto_prefix=True)
        pid = eng.register_prefix([5, 17, 42])
        blocker = eng.submit([8, 8], max_new_tokens=3)     # occupies slot 0
        h = eng.submit([5, 17, 42, 9], max_new_tokens=4)   # queued, matched
        eng.unregister_prefix(pid)                          # evicted in-flight
        while eng.step():
            pass
        want = _reference_tokens(params, cfg, [5, 17, 42, 9], 4)
        assert blocker.result(timeout=0) == _reference_tokens(
            params, cfg, [8, 8], 3)
        assert h.result(timeout=0) == want
        assert eng._prefix_hits == 0


class TestChunkedPrefill:
    """prefill_chunk=C: a prompt longer than C admits over multiple engine
    steps — one C-token chunk of prefill between decode blocks — via the
    prefix-suffix math, so a long admission never stalls active streams
    for more than one chunk. Contract: bit-exact vs the one-shot engine
    for dense models, neighbors unaffected, cancel honored mid-chunk."""

    def test_long_prompt_exact_with_active_neighbor(self, dense):
        params, cfg = dense
        long_prompt = list(range(5, 16))            # 11 tokens → 4+4+3
        want = _reference_tokens(params, cfg, long_prompt, 6)
        nbr_want = _reference_tokens(params, cfg, [1, 2], 8)
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 16), prefill_chunk=4,
                               decode_block=2)
        nbr = eng.submit([1, 2], max_new_tokens=8)
        h = eng.submit(long_prompt, max_new_tokens=6)
        while eng.step():
            pass
        assert h.result(timeout=0) == want
        assert nbr.result(timeout=0) == nbr_want

    def test_short_prompt_still_one_shot(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(4,), prefill_chunk=4)
        want = _reference_tokens(params, cfg, [7, 8], 4)
        h = eng.submit([7, 8], max_new_tokens=4)
        while eng.step():
            pass
        assert h.result(timeout=0) == want

    def test_chunked_behind_registered_prefix(self, dense):
        """A cached prefix seeds the accumulator; the long suffix chunks
        in behind it at the right positions."""
        params, cfg = dense
        prefix = [5, 17, 42]
        suffix = list(range(30, 39))                 # 9 tokens → 4+4+1
        want = _reference_tokens(params, cfg, prefix + suffix, 5)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4, 8), prefill_chunk=4,
                               auto_prefix=True)
        eng.register_prefix(prefix)
        h = eng.submit(prefix + suffix, max_new_tokens=5)
        while eng.step():
            pass
        assert h.result(timeout=0) == want
        assert eng._prefix_hits == 1

    def test_chunked_penalties_match_one_shot(self, dense):
        params, cfg = dense
        long_prompt = list(range(50, 60))
        runs = []
        for chunk in (None, 4):
            eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4, 16),
                                   prefill_chunk=chunk)
            h = eng.submit(long_prompt, max_new_tokens=8,
                           frequency_penalty=0.7, presence_penalty=0.3)
            while eng.step():
                pass
            runs.append(h.result(timeout=0))
        assert runs[0] == runs[1]

    def test_chunked_quantized_kv(self, dense):
        params, cfg = dense
        long_prompt = list(range(5, 14))
        runs = []
        for chunk in (None, 4):
            eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4, 16),
                                   prefill_chunk=chunk, quantize_kv=True)
            h = eng.submit(long_prompt, max_new_tokens=6)
            while eng.step():
                pass
            runs.append(h.result(timeout=0))
        assert runs[0] == runs[1]

    def test_cancel_mid_chunking(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4, 16), prefill_chunk=4)
        nbr_want = _reference_tokens(params, cfg, [1, 2], 6)
        nbr = eng.submit([1, 2], max_new_tokens=6)
        h = eng.submit(list(range(5, 16)), max_new_tokens=6)
        eng.step()                     # chunk 1 ran; admission in flight
        assert h.cancel() is True
        while eng.step():
            pass
        assert h.result(timeout=0) == []     # stream ended, no tokens
        assert nbr.result(timeout=0) == nbr_want
        # the reserved slot was released: a new request admits and runs
        w2 = _reference_tokens(params, cfg, [9], 3)
        h2 = eng.submit([9], max_new_tokens=3)
        while eng.step():
            pass
        assert h2.result(timeout=0) == w2

    def test_spec_engine_refuses_prefill_chunk(self, dense):
        params, cfg = dense
        from kubetorch_tpu.serve.spec_engine import SpeculativeEngine
        with pytest.raises(ValueError, match="chunked prefill"):
            SpeculativeEngine(params, cfg, params, cfg, prefill_chunk=4)

    def test_chunked_sampled_mode_matches_one_shot(self, dense):
        """Intermediate chunks use a constant dummy key, so the engine's
        key-split stream is IDENTICAL to one-shot admission — sampled
        requests (same seed) decode the same tokens either way."""
        params, cfg = dense
        long_prompt = list(range(40, 51))
        runs = []
        for chunk in (None, 4):
            eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4, 16),
                                   prefill_chunk=chunk, seed=11)
            h = eng.submit(long_prompt, max_new_tokens=8, temperature=0.9,
                           top_p=0.8)
            while eng.step():
                pass
            runs.append(h.result(timeout=0))
        assert runs[0] == runs[1]

    def test_chunked_fills_to_exact_max_len(self, dense):
        """A prompt whose accumulated chunks reach the max_len boundary
        (chunk width not dividing the budget) still admits: the fixed
        max_len-capacity accumulator makes the final splice exact."""
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32,
                               prefill_buckets=(4, 32), prefill_chunk=8)
        prompt = list(range(1, 30))          # 29 tokens; 29+1 <= 32
        want = _reference_tokens(params, cfg, prompt, 1)
        h = eng.submit(prompt, max_new_tokens=1)
        while eng.step():
            pass
        assert h.result(timeout=0) == want

    def test_chunked_prefix_plus_long_suffix_at_boundary(self, dense):
        """Registered prefix (bucket 4) + 59-token suffix at max_len=64:
        submit validates 4+59+1 <= 64 and the chunked path must not
        overflow the cache width."""
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,), prefill_chunk=8,
                               auto_prefix=True)
        prefix = [5, 17, 42]
        eng.register_prefix(prefix)
        suffix = list(range(100, 159))       # 59 tokens
        h = eng.submit(prefix + suffix, max_new_tokens=1)
        while eng.step():
            pass
        got = h.result(timeout=0)
        assert len(got) == 1 and eng._prefix_hits == 1
        assert got == _reference_tokens(params, cfg, prefix + suffix, 1)


    def test_two_long_prompts_queue_for_the_chunker(self, dense,
                                                    monkeypatch):
        """A second long prompt while the chunker is busy waits for it
        (never a one-shot prefill at a wide bucket) and both match their
        oracles. A width spy proves every prefill ran at the CHUNK width —
        the regression (falling back to one-shot) would show width 16."""
        import kubetorch_tpu.serve.engine as eng_mod
        params, cfg = dense
        widths = []
        real_prefill = eng_mod._prefill

        def spy(params_, tokens, *a, **kw):
            widths.append(tokens.shape[1])
            return real_prefill(params_, tokens, *a, **kw)

        monkeypatch.setattr(eng_mod, "_prefill", spy)
        p1 = list(range(5, 16))
        p2 = list(range(60, 73))
        w1 = _reference_tokens(params, cfg, p1, 5)
        w2 = _reference_tokens(params, cfg, p2, 5)
        eng = GenerationEngine(params, cfg, slots=4, max_len=64,
                               prefill_buckets=(4, 16), prefill_chunk=4)
        h1 = eng.submit(p1, max_new_tokens=5)
        h2 = eng.submit(p2, max_new_tokens=5)
        while eng.step():
            pass
        assert h1.result(timeout=0) == w1
        assert h2.result(timeout=0) == w2
        assert widths == [4, 4], widths   # first chunks only, chunk-wide


class TestLogitBias:
    """OpenAI logit_bias: per-request additive bias on the logits, applied
    at the prefill sampling and every decode step. Slot-isolated (mask
    neutralizes stale rows) and reported logprobs stay raw-model."""

    def test_positive_bias_forces_token(self, dense):
        params, cfg = dense
        prompt = [5, 17, 42]
        solo = _reference_tokens(params, cfg, prompt, 6)
        forced = (solo[0] + 123) % cfg.vocab_size     # not the greedy pick
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,))
        h = eng.submit(prompt, max_new_tokens=6,
                       logit_bias={forced: 1000.0})
        while eng.step():
            pass
        assert h.result(timeout=0) == [forced] * 6    # prefill + decode

    def test_negative_bias_suppresses_token(self, dense):
        params, cfg = dense
        prompt = [5, 17, 42]
        solo = _reference_tokens(params, cfg, prompt, 6)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        h = eng.submit(prompt, max_new_tokens=6,
                       logit_bias={solo[0]: -1000.0})
        while eng.step():
            pass
        got = h.result(timeout=0)
        assert solo[0] not in got and got != solo

    def test_bias_is_slot_isolated_and_cleared_on_reuse(self, dense):
        params, cfg = dense
        prompt = [5, 17, 42]
        solo = _reference_tokens(params, cfg, prompt, 6)
        forced = (solo[0] + 7) % cfg.vocab_size
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,))
        hb = eng.submit(prompt, max_new_tokens=6,
                        logit_bias={forced: 1000.0})
        hn = eng.submit(prompt, max_new_tokens=6)     # unbiased neighbor
        while eng.step():
            pass
        assert hb.result(timeout=0) == [forced] * 6
        assert hn.result(timeout=0) == solo
        # slot reuse: the retired biased slot's stale row must not leak
        h2 = eng.submit(prompt, max_new_tokens=6)
        h3 = eng.submit(prompt, max_new_tokens=6)
        while eng.step():
            pass
        assert h2.result(timeout=0) == solo
        assert h3.result(timeout=0) == solo

    def test_bias_block_path_matches_one_step(self, dense):
        params, cfg = dense
        prompt = [9, 9, 9]
        runs = []
        for block in (1, 4):
            eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4,),
                                   decode_block=block)
            h = eng.submit(prompt, max_new_tokens=7,
                           logit_bias={3: 5.0, 11: -5.0})
            while eng.step():
                pass
            runs.append(h.result(timeout=0))
        assert runs[0] == runs[1]

    def test_bias_validates_vocab_range(self, dense):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=1, max_len=32)
        with pytest.raises(ValueError, match="vocab"):
            eng.submit([1, 2], max_new_tokens=2,
                       logit_bias={cfg.vocab_size + 5: 1.0})



class TestPerRequestSeed:
    """submit(..., seed=S): the sampled stream is a pure function of
    (seed, prompt positions) — invariant to slot placement, neighbors,
    engine seed, decode_block, and admission order."""

    def _run(self, dense, engine_seed, neighbors, seed, block=1,
             chunk=None, prompt=(3, 4)):
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=4, max_len=64,
                               prefill_buckets=(4, 16), seed=engine_seed,
                               decode_block=block, prefill_chunk=chunk)
        for p in neighbors:
            eng.submit(p, max_new_tokens=5, temperature=1.0)
        h = eng.submit(list(prompt), max_new_tokens=6, temperature=1.0,
                       seed=seed)
        while eng.step():
            pass
        return h.result(timeout=0)

    def test_seed_invariant_to_everything_else(self, dense):
        a = self._run(dense, 0, [[1, 1]], 42)
        b = self._run(dense, 7, [[9, 9], [2, 2]], 42)   # slot 2, new chain
        d = self._run(dense, 0, [[1, 1]], 42, block=4)
        ch = self._run(dense, 0, [[1, 1]], 42, chunk=4,
                       prompt=tuple(range(3, 14)))
        ch2 = self._run(dense, 3, [], 42, chunk=4,
                        prompt=tuple(range(3, 14)))
        assert a == b == d
        assert ch == ch2                                 # chunked too
        assert a != self._run(dense, 0, [[1, 1]], 43)    # seeds diverge

    def test_greedy_ignores_seed(self, dense):
        params, cfg = dense
        want = _reference_tokens(params, cfg, [5, 17, 42], 6)
        eng = GenerationEngine(params, cfg, slots=1, max_len=64,
                               prefill_buckets=(4,))
        h = eng.submit([5, 17, 42], max_new_tokens=6, temperature=0.0,
                       seed=99)
        while eng.step():
            pass
        assert h.result(timeout=0) == want

    def test_openai_seed_reproducible_over_the_wire(self, dense):
        import asyncio
        from aiohttp.test_utils import TestClient, TestServer
        from kubetorch_tpu.serve.openai_api import build_app
        params, cfg = dense
        eng = GenerationEngine(params, cfg, slots=2, max_len=64,
                               prefill_buckets=(4,)).start()

        async def body():
            client = TestClient(TestServer(build_app(eng)))
            await client.start_server()
            outs = []
            for _ in range(2):
                r = await client.post("/v1/completions", json={
                    "prompt": [5, 17, 42], "max_tokens": 5,
                    "temperature": 1.0, "seed": 1234})
                outs.append((await r.json())["choices"][0]["token_ids"])
            await client.close()
            return outs

        try:
            outs = asyncio.run(body())
        finally:
            eng.stop()
        assert outs[0] == outs[1]


def test_ttft_stat_populates(dense):
    params, cfg = dense
    eng = GenerationEngine(params, cfg, slots=2, max_len=32,
                           prefill_buckets=(4,))
    assert eng.stats().ttft_avg == 0.0
    h = eng.submit([1, 2], max_new_tokens=3)
    while eng.step():
        pass
    s = eng.stats()
    assert s.ttft_avg > 0.0
    assert abs(s.ttft_avg - h.time_to_first_token()) < 1e-6
    assert eng.__kt_metrics__()["engine_ttft_avg_seconds"] == s.ttft_avg
