"""Serving front-door suite (ISSUE 9): router packing/affinity/admission,
deadline shedding BEFORE prefill, the health TTL cache, session glue, and
the queue-wait autoscaler's histogram math. ``make test-serve``."""

import asyncio
import json
import os
import time

import pytest

from kubetorch_tpu import telemetry
from kubetorch_tpu.constants import (PRIORITY_HEADER, SESSION_HEADER)
from kubetorch_tpu.exceptions import (AdmissionShedError,
                                      DeadlineExceededError, WorkerCallError,
                                      package_exception, rehydrate_exception)
from kubetorch_tpu.resilience import DEADLINE_HEADER
from kubetorch_tpu.serving.router import (HealthCache, Router, SessionTable,
                                          affinity_key)

pytestmark = pytest.mark.serve

IPS = ["10.1.0.1", "10.1.0.2", "10.1.0.3"]
MY_IP = "9.9.9.9"          # the router host itself is not a replica here


class FakePool:
    """The RemoteWorkerPool surface, scripted: per-ip health, per-ip
    transport failure, optional per-ip blocking (to hold slots busy)."""

    def __init__(self):
        self.health = {}              # ip -> bool (default True)
        self.fail = set()             # ips that raise WorkerCallError
        self.block = {}               # ip -> asyncio.Event gating return
        self.app_error = set()        # ips that raise an app exception
        self.health_calls = []
        self.calls = []

    async def check_health(self, ip, timeout=2.0):
        self.health_calls.append(ip)
        return self.health.get(ip, True)

    async def call_worker(self, ip, fn_name, method, body, headers,
                          timeout=None, subtree=None, sel_ips=None):
        self.calls.append(ip)
        if ip in self.fail:
            raise WorkerCallError(f"worker {ip} unreachable", worker=ip)
        if ip in self.app_error:
            raise ValueError("application failure from the replica")
        ev = self.block.get(ip)
        if ev is not None:
            await ev.wait()
        return {"served_by": ip}


async def _local_call(method, args, kwargs, timeout):
    return {"served_by": "local"}


def _dispatch(router, pool, headers=None, kwargs=None, ips=None,
              my_ip=MY_IP):
    return router.dispatch(pool=pool, ips=ips or IPS, my_ip=my_ip,
                           method=None, args=[], kwargs=kwargs or {},
                           headers=headers, timeout=None,
                           local_call=_local_call)


def _counter(key, **labels):
    # through serve_metrics() so the labeled family exists before any
    # read (a bare REGISTRY.counter(name) would declare it label-less)
    return telemetry.serve_metrics()[key].value(**labels)


# ---------------------------------------------------------------------------
# selection: packing + affinity
# ---------------------------------------------------------------------------


def test_idle_fleet_rotates_round_robin():
    """Sequential keyless traffic on an idle fleet degenerates to the old
    round-robin — every replica sees work."""
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        for _ in range(len(IPS) * 2):
            await _dispatch(router, pool)
        return pool.calls
    calls = asyncio.run(body())
    assert set(calls) == set(IPS)


def test_concurrent_keyless_requests_pack_into_partial_batches():
    """Continuous batching across replicas: while a replica has a
    partially-full batch, new keyless requests join IT rather than
    spreading one-deep everywhere."""
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        for ip in IPS:
            pool.block[ip] = asyncio.Event()
        t1 = asyncio.ensure_future(_dispatch(router, pool))
        await asyncio.sleep(0.01)
        first = pool.calls[0]
        t2 = asyncio.ensure_future(_dispatch(router, pool))
        t3 = asyncio.ensure_future(_dispatch(router, pool))
        await asyncio.sleep(0.01)
        for ev in pool.block.values():
            ev.set()
        await asyncio.gather(t1, t2, t3)
        return first, pool.calls
    first, calls = asyncio.run(body())
    assert calls == [first] * 3, \
        f"requests spread instead of packing: {calls}"


def test_packed_replica_overflows_to_next_when_full():
    async def body():
        router = Router(slots_per_replica=2, health_ttl_s=60)
        pool = FakePool()
        for ip in IPS:
            pool.block[ip] = asyncio.Event()
        tasks = [asyncio.ensure_future(_dispatch(router, pool))
                 for _ in range(3)]
        await asyncio.sleep(0.02)
        seen = list(pool.calls)
        for ev in pool.block.values():
            ev.set()
        await asyncio.gather(*tasks)
        return seen
    seen = asyncio.run(body())
    # 2 pack into the first replica's batch, the 3rd overflows elsewhere
    assert len(seen) == 3 and seen[0] == seen[1] and seen[2] != seen[0]


def test_affinity_session_sticks_and_counts():
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        h = {SESSION_HEADER: "sess-A"}
        hit0 = _counter("affinity", result="hit")
        miss0 = _counter("affinity", result="miss")
        first = await _dispatch(router, pool, headers=h)
        out = [await _dispatch(router, pool, headers=h) for _ in range(3)]
        hits = _counter("affinity", result="hit") - hit0
        misses = _counter("affinity", result="miss") - miss0
        return first, out, hits, misses
    first, out, hits, misses = asyncio.run(body())
    assert all(o == first for o in out), "session moved between replicas"
    assert misses == 1 and hits == 3    # cold placement once, then resident


def test_cold_placement_is_consistent_hash_across_routers():
    """Two independent routers (different pods' front doors) place the
    same cold session on the same replica — residency accretes in one
    place with zero coordination."""
    async def body():
        pool = FakePool()
        homes = []
        for _ in range(2):
            router = Router(slots_per_replica=4, health_ttl_s=60)
            out = await _dispatch(router, pool,
                                  headers={SESSION_HEADER: "sess-X"})
            homes.append(out["served_by"])
        return homes
    homes = asyncio.run(body())
    assert homes[0] == homes[1]


def test_failover_on_transport_error_evicts_sessions():
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        h = {SESSION_HEADER: "sess-B"}
        first = (await _dispatch(router, pool, headers=h))["served_by"]
        pool.fail.add(first)
        second = (await _dispatch(router, pool, headers=h))["served_by"]
        # the dead replica's residency is forgotten; the session now lives
        # on the failover target and stays there
        third = (await _dispatch(router, pool, headers=h))["served_by"]
        return first, second, third
    first, second, third = asyncio.run(body())
    assert second != first and third == second


def test_application_errors_propagate_without_failover():
    """An app exception from the chosen replica must surface, never re-run
    a (possibly non-idempotent) call on another pod."""
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        pool.app_error = set(IPS)
        with pytest.raises(ValueError):
            await _dispatch(router, pool)
        return pool.calls
    calls = asyncio.run(body())
    assert len(calls) == 1


def test_all_replicas_dead_falls_back_to_local():
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        pool.health = {ip: False for ip in IPS}
        return await _dispatch(router, pool)
    assert asyncio.run(body())["served_by"] == "local"


# ---------------------------------------------------------------------------
# health TTL cache (satellite: the per-dispatch probe RTT fix)
# ---------------------------------------------------------------------------


def test_health_cache_avoids_per_dispatch_probes():
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        avoided0 = _counter("probes_avoided")
        for _ in range(6):
            await _dispatch(router, pool)
        avoided = _counter("probes_avoided") - avoided0
        return pool.health_calls, avoided
    health_calls, avoided = asyncio.run(body())
    # one real probe per replica; everything else served from the cache
    assert len(health_calls) <= len(IPS)
    assert avoided >= 3


def test_health_cache_ttl_expires_and_error_marks_down():
    async def body():
        cache = HealthCache(ttl_s=0.05)
        pool = FakePool()
        assert await cache.healthy(pool, "10.0.0.9")
        assert await cache.healthy(pool, "10.0.0.9")   # cached
        n_cached = len(pool.health_calls)
        await asyncio.sleep(0.06)
        assert await cache.healthy(pool, "10.0.0.9")   # TTL lapsed: probe
        n_expired = len(pool.health_calls)
        cache.mark_down("10.0.0.9")
        # a failed CALL is stronger evidence than any probe: down without
        # probing, for a full TTL
        assert not await cache.healthy(pool, "10.0.0.9")
        return n_cached, n_expired, len(pool.health_calls)
    n_cached, n_expired, n_final = asyncio.run(body())
    assert n_cached == 1 and n_expired == 2 and n_final == 2


# ---------------------------------------------------------------------------
# admission control + shedding
# ---------------------------------------------------------------------------


def test_expired_deadline_shed_at_door_without_touching_replicas():
    async def body():
        router = Router(slots_per_replica=4, health_ttl_s=60)
        pool = FakePool()
        with pytest.raises(DeadlineExceededError):
            await _dispatch(router, pool, headers={
                DEADLINE_HEADER: f"{time.time() - 1.0:.6f}"})
        return pool.calls, pool.health_calls
    calls, health_calls = asyncio.run(body())
    assert calls == [] and health_calls == []


def test_doomed_request_sheds_with_429_semantics():
    async def body():
        ips = [IPS[0]]
        router = Router(slots_per_replica=1, health_ttl_s=60)
        pool = FakePool()
        pool.block[ips[0]] = asyncio.Event()
        t1 = asyncio.ensure_future(
            _dispatch(router, pool, ips=ips))           # holds the slot
        await asyncio.sleep(0.01)
        t2 = asyncio.ensure_future(
            _dispatch(router, pool, ips=ips))           # queues
        await asyncio.sleep(0.01)
        router._ewma_s = 5.0          # measured service time: 5s/request
        with pytest.raises(AdmissionShedError) as ei:
            await _dispatch(router, pool, ips=ips, headers={
                DEADLINE_HEADER: f"{time.time() + 0.5:.6f}"})
        pool.block[ips[0]].set()
        await asyncio.gather(t1, t2)
        return ei.value
    err = asyncio.run(body())
    assert err.reason == "doomed" and err.retry_after > 0.5
    # and it round-trips typed through the exception registry (what the
    # HTTP 429 body carries)
    back = rehydrate_exception(package_exception(err))
    assert isinstance(back, AdmissionShedError)
    assert back.reason == "doomed" and back.retry_after == err.retry_after


def test_queue_full_sheds_lowest_tier_first():
    async def body():
        ips = [IPS[0]]
        router = Router(slots_per_replica=1, queue_max=1, health_ttl_s=60)
        pool = FakePool()
        pool.block[ips[0]] = asyncio.Event()
        holder = asyncio.ensure_future(_dispatch(router, pool, ips=ips))
        await asyncio.sleep(0.01)
        batch = asyncio.ensure_future(_dispatch(
            router, pool, ips=ips, headers={PRIORITY_HEADER: "batch"}))
        await asyncio.sleep(0.01)
        # a batch-tier arrival against a full queue sheds ITSELF
        with pytest.raises(AdmissionShedError) as low:
            await _dispatch(router, pool, ips=ips,
                            headers={PRIORITY_HEADER: "batch"})
        # a high-tier arrival evicts the queued batch request instead
        high = asyncio.ensure_future(_dispatch(
            router, pool, ips=ips, headers={PRIORITY_HEADER: "high"}))
        await asyncio.sleep(0.01)
        with pytest.raises(AdmissionShedError) as evicted:
            await batch
        pool.block[ips[0]].set()
        await asyncio.gather(holder, high)
        return low.value, evicted.value
    low, evicted = asyncio.run(body())
    assert low.reason == "queue_full" and low.tier == "batch"
    assert evicted.reason == "queue_full" and evicted.tier == "batch"


def test_admission_queue_observes_queue_wait_stage():
    async def body():
        ips = [IPS[0]]
        router = Router(slots_per_replica=1, health_ttl_s=60)
        pool = FakePool()
        pool.block[ips[0]] = asyncio.Event()
        before = telemetry.stage_histogram().count(stage="queue_wait")
        holder = asyncio.ensure_future(_dispatch(router, pool, ips=ips))
        await asyncio.sleep(0.01)
        queued = asyncio.ensure_future(_dispatch(router, pool, ips=ips))
        await asyncio.sleep(0.01)
        pool.block[ips[0]].set()
        await asyncio.gather(holder, queued)
        return telemetry.stage_histogram().count(stage="queue_wait") - before
    assert asyncio.run(body()) >= 1


# ---------------------------------------------------------------------------
# SessionTable
# ---------------------------------------------------------------------------


def test_session_table_lru_ttl_and_replica_eviction():
    t = SessionTable(capacity=2, ttl_s=0.05)
    t.touch("a", "ip1")
    t.touch("b", "ip2")
    assert t.lookup("a") == "ip1"
    t.touch("c", "ip1")                   # capacity 2: LRU "b" evicted
    assert t.lookup("b") is None
    assert t.evict_replica("ip1") == 2    # a + c forgotten with the pod
    t.touch("d", "ip3")
    time.sleep(0.06)
    assert t.lookup("d") is None          # TTL lapsed


def test_affinity_key_extraction():
    assert affinity_key({SESSION_HEADER: "s1"}, {}) == "s1"
    assert affinity_key({}, {"session_id": 7}) == "session_id:7"
    assert affinity_key({}, {"adapter_id": 3}) == "adapter_id:3"
    assert affinity_key({}, {"x": 1}) is None
    # header wins over kwargs
    assert affinity_key({SESSION_HEADER: "s1"},
                        {"session_id": 7}) == "s1"


# ---------------------------------------------------------------------------
# serve/sessions.py — the engine-side glue
# ---------------------------------------------------------------------------


class FakeEngine:
    def __init__(self):
        self.next_pid = 0
        self.registered = {}          # pid -> (tokens, adapter)
        self.submits = []             # (prompt, prefix_id, adapter_id)

    def register_prefix(self, tokens, adapter_id=None):
        pid = self.next_pid
        self.next_pid += 1
        self.registered[pid] = (list(tokens), adapter_id)
        return pid

    def unregister_prefix(self, pid):
        return self.registered.pop(pid, None) is not None

    def submit(self, prompt, prefix_id=None, adapter_id=None, **kw):
        self.submits.append((list(prompt), prefix_id, adapter_id))
        return f"handle-{len(self.submits)}"


def test_binder_reuses_session_prefix_for_later_turns():
    from kubetorch_tpu.serve.sessions import EngineSessionBinder
    eng = FakeEngine()
    b = EngineSessionBinder(eng, capacity=4, min_prefix_tokens=2)
    turn1 = list(range(20))
    b.submit("s1", turn1)
    assert eng.submits[-1] == (turn1, None, None)      # cold: full prefill
    assert len(eng.registered) == 1                    # turn 1 now resident
    turn2 = turn1 + [100, 101, 102]
    b.submit("s1", turn2)
    # only the suffix prefills, against the resident prefix
    assert eng.submits[-1] == ([100, 101, 102], 0, None)
    s = b.stats()
    assert s.hits == 1 and s.misses == 1 and s.sessions == 1


def test_binder_adapter_mismatch_is_a_miss():
    from kubetorch_tpu.serve.sessions import EngineSessionBinder
    eng = FakeEngine()
    b = EngineSessionBinder(eng, capacity=4, min_prefix_tokens=2)
    prompt = list(range(10))
    b.submit("s1", prompt, adapter_id=None)
    b.submit("s1", prompt + [99], adapter_id=7)        # different adapter
    assert eng.submits[-1][1] is None                  # no prefix reuse
    assert b.stats().misses == 2


def test_binder_lru_eviction_unregisters_device_state():
    from kubetorch_tpu.serve.sessions import EngineSessionBinder
    eng = FakeEngine()
    b = EngineSessionBinder(eng, capacity=2, min_prefix_tokens=2)
    for i in range(3):
        b.submit(f"s{i}", list(range(10 + i)))
    assert len(eng.registered) == 2                    # LRU evicted + freed
    assert b.stats().evictions == 1
    assert b.release("s2") and len(eng.registered) == 1
    metrics = b.__kt_metrics__()
    assert metrics["sessions_resident"] == 1.0


# ---------------------------------------------------------------------------
# queue-wait autoscaler math (controller)
# ---------------------------------------------------------------------------


def test_histogram_bucket_parse_and_quantile():
    from kubetorch_tpu.controller.app import (_parse_histogram_buckets,
                                              _quantile_from_buckets)
    h = telemetry.Histogram("t_qw", "", ("stage",),
                            buckets=(0.1, 0.5, 1.0))
    for v in (0.05, 0.05, 0.3, 0.3, 0.3, 0.7, 0.7, 0.9, 2.0, 2.0):
        h.observe(v, stage="queue_wait")
    text = "\n".join(h.render()) + "\n"
    buckets = _parse_histogram_buckets(text, "t_qw", 'stage="queue_wait"')
    assert buckets["+Inf"] == 10 and buckets["0.1"] == 2
    p50 = _quantile_from_buckets(buckets, 0.5)
    assert 0.1 < p50 <= 0.5
    # p90 falls in the +Inf bucket: clamps to the last finite edge
    assert _quantile_from_buckets(buckets, 0.95) == 1.0
    assert _quantile_from_buckets({}, 0.9) is None


def test_serve_slo_resolution():
    from kubetorch_tpu.controller.app import _serve_slo_s
    assert _serve_slo_s({}) == 0.0                     # default: disabled
    assert _serve_slo_s({"slo_ms": 250}) == 0.25
    os.environ["KT_SERVE_SLO_MS"] = "100"
    try:
        assert _serve_slo_s({}) == 0.1
        assert _serve_slo_s({"slo_ms": 500}) == 0.5    # per-service wins
    finally:
        del os.environ["KT_SERVE_SLO_MS"]
    assert _serve_slo_s({"slo_ms": "junk"}) == 0.0


def test_chaos_shed_verb_parses():
    from kubetorch_tpu.chaos import parse_spec
    faults = parse_spec("shed:0.5,shed")
    assert [f.kind for f in faults] == ["shed", "shed"]
    assert faults[0].retry_after == 0.5 and faults[1].retry_after is None


# ---------------------------------------------------------------------------
# shed-before-prefill, end to end through the pod server (satellite 3):
# chaos delays the request past its deadline BEFORE dispatch; the typed
# error rehydrates client-side and NO execute stage span exists.
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_shed_before_prefill_no_execute_span():
    from kubetorch_tpu.serving.env_contract import METADATA_KEYS

    from .test_http_server import run_server_test, set_fn_metadata

    saved = {k: os.environ.get(k) for k in METADATA_KEYS}
    os.environ["KT_CHAOS"] = "delay:0.25"
    try:
        async def body(client, state):
            set_fn_metadata("summer")
            state.launch_id = "launch-1"
            state.prewarm_supervisor()
            telemetry.RING.clear()
            # expires DURING the injected pre-dispatch delay: the deadline
            # middleware sheds it before run_callable ever runs
            r = await client.post(
                "/summer", json={"args": [1, 2], "kwargs": {}},
                headers={DEADLINE_HEADER: f"{time.time() + 0.05:.6f}"})
            assert r.status == 504
            rid = r.headers["X-Request-ID"]
            err = rehydrate_exception(json.loads(await r.text()))
            assert isinstance(err, DeadlineExceededError)
            spans = telemetry.RING.find(rid)
            names = [s["name"] for s in spans]
            assert "server.request" in names, names
            assert "stage.execute" not in names, \
                f"shed request still burned prefill compute: {names}"
            assert "stage.deserialize" not in names

            # control: the schedule is exhausted, so the next request runs
            # normally — and DOES emit the execute span (the assertion
            # above is not vacuous)
            r = await client.post("/summer",
                                  json={"args": [1, 2], "kwargs": {}})
            assert r.status == 200 and await r.json() == 3
            rid2 = r.headers["X-Request-ID"]
            names2 = [s["name"] for s in telemetry.RING.find(rid2)]
            assert "stage.execute" in names2, names2
        run_server_test(body)
    finally:
        del os.environ["KT_CHAOS"]
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
