"""Zero-copy shared-memory envelopes (ISSUE 10): ring protocol, envelope
encode/decode with sampled blake2b verification, the chaos ``shm-corrupt``
drill (decode raises typed ``DataCorruptionError(source="shm")`` and the
pool falls back to the queue path), and the /dev/shm lifecycle contract —
a dead rank leaks no segments, ring-full degrades to the queue path, and
``KT_SHM_THRESHOLD`` unset/0 disables the path byte-identically.
"""

import asyncio
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.level("minimal")

from kubetorch_tpu.chaos import ChaosEngine, parse_spec, shm_corrupt_plan
from kubetorch_tpu.exceptions import DataCorruptionError
from kubetorch_tpu.resources.pointers import Pointers
from kubetorch_tpu.serving import shm_ring
from kubetorch_tpu.serving.process_pool import ProcessPool
from kubetorch_tpu.serving.shm_ring import SHM_KEY, ShmRing

ASSETS = os.path.join(os.path.dirname(__file__), "assets")


def _pointers(fn="summer"):
    return Pointers(project_root=ASSETS, module_name="payloads",
                    file_path="payloads.py", cls_or_fn_name=fn)


def _segments():
    try:
        return {f for f in os.listdir("/dev/shm") if f.startswith("kt-shm-")}
    except OSError:
        return set()


def _wait_until(predicate, timeout=45.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def ring():
    r = ShmRing(shm_ring.make_name("test"), size=1 << 20, create=True)
    yield r
    r.close()
    r.unlink()


# ---------------------------------------------------------------------------
# Ring protocol units
# ---------------------------------------------------------------------------


def test_ring_put_view_free_roundtrip(ring):
    data = np.arange(256, dtype=np.uint8)
    pos = ring.try_put(data)
    assert pos == 0
    np.testing.assert_array_equal(np.asarray(ring.view(pos, 256)), data)
    assert ring.used() == 256
    ring.free(pos, 256)
    assert ring.used() == 0


def test_ring_full_returns_none(ring):
    cap = ring.data_size
    big = np.zeros(cap, dtype=np.uint8)
    pos = ring.try_put(big)
    assert pos is not None
    # unconsumed window is full: the next allocation must fail cleanly
    assert ring.try_put(np.zeros(1, dtype=np.uint8)) is None
    ring.free(pos, cap)
    assert ring.try_put(np.zeros(1, dtype=np.uint8)) is not None


def test_ring_oversized_block_rejected(ring):
    assert ring.try_put(np.zeros(ring.data_size + 1, dtype=np.uint8)) is None


def test_ring_blocks_never_wrap(ring):
    """An allocation that would straddle the end skips to the next lap;
    the monotonic free jumps the gap implicitly."""
    cap = ring.data_size
    a = np.ones(cap - 100, dtype=np.uint8)
    p1 = ring.try_put(a)
    ring.free(p1, a.nbytes)
    b = np.full(400, 7, dtype=np.uint8)
    p2 = ring.try_put(b)                 # only 100B left before the edge
    assert p2 == cap                     # skipped to the next lap
    np.testing.assert_array_equal(np.asarray(ring.view(p2, 400)), b)
    ring.free(p2, 400)
    assert ring.used() == 0


def test_ring_attach_sees_writes(ring):
    data = np.frombuffer(b"hello shm ring", dtype=np.uint8)
    pos = ring.try_put(data)
    peer = ShmRing(ring.name)            # attach by name, same process
    try:
        assert bytes(np.asarray(peer.view(pos, len(data)))) == bytes(data)
    finally:
        peer.close()


# ---------------------------------------------------------------------------
# Envelope encode/decode
# ---------------------------------------------------------------------------


def test_encode_decode_roundtrip(ring, monkeypatch):
    monkeypatch.setenv("KT_SHM_VERIFY", "all")
    arr = np.random.default_rng(0).standard_normal(4096).astype(np.float32)
    item = {"args": [arr, 5, "x"], "kwargs": {"w": {"deep": arr * 2}}}
    n = shm_ring.encode_item_fields(item, ring, ("args", "kwargs"),
                                    1024, "req")
    assert n == 2
    assert SHM_KEY in item["args"][0]
    assert item["args"][1] == 5          # scalars stay inline
    assert shm_ring.decode_item_fields(item, ring, ("args", "kwargs"),
                                       "req") == 2
    np.testing.assert_array_equal(item["args"][0], arr)
    np.testing.assert_array_equal(item["kwargs"]["w"]["deep"], arr * 2)
    assert item["args"][0].dtype == np.float32
    assert ring.used() == 0              # every slot freed on decode


def test_encode_below_threshold_is_identity(ring):
    arr = np.zeros(16, dtype=np.float32)
    args = [arr]
    item = {"args": args}
    assert shm_ring.encode_item_fields(item, ring, ("args",),
                                       1 << 20, "req") == 0
    assert item["args"] is args          # untouched, not rebuilt
    assert ring.used() == 0


def test_encode_no_shm_flag_short_circuits(ring):
    item = {"args": [np.zeros(4096, dtype=np.float32)], "no_shm": True}
    assert shm_ring.encode_item_fields(item, ring, ("args",), 16, "req") == 0


def test_encode_ring_full_falls_back_inline(ring):
    """An array bigger than the ring stays inline on the queue — the call
    still works, nothing raises."""
    arr = np.zeros(ring.data_size + 64, dtype=np.uint8)
    item = {"args": [arr]}
    assert shm_ring.encode_item_fields(item, ring, ("args",), 16, "req") == 0
    assert item["args"][0] is arr


def test_decode_hash_mismatch_raises_typed(ring, monkeypatch):
    monkeypatch.setenv("KT_SHM_VERIFY", "all")
    arr = np.arange(1024, dtype=np.float32)
    item = {"args": [arr]}
    assert shm_ring.encode_item_fields(item, ring, ("args",), 16,
                                       "req") == 1
    spec = item["args"][0][SHM_KEY]
    off = ring.DATA_OFF + (spec["pos"] % ring.data_size)
    ring.shm.buf[off] ^= 0xFF            # rot one byte in the segment
    with pytest.raises(DataCorruptionError) as ei:
        shm_ring.decode_item_fields(item, ring, ("args",), "req")
    assert ei.value.source == "shm" and ei.value.key == "req"
    assert ring.used() == 0              # slot freed even on corruption


def test_bfloat16_envelope_roundtrip(ring, monkeypatch):
    monkeypatch.setenv("KT_SHM_VERIFY", "all")
    import ml_dtypes
    arr = np.arange(2048, dtype=np.float32).astype(ml_dtypes.bfloat16)
    item = {"result": arr}
    assert shm_ring.encode_item_fields(item, ring, ("result",), 16,
                                       "resp") == 1
    assert shm_ring.decode_item_fields(item, ring, ("result",),
                                       "resp") == 1
    assert item["result"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        item["result"].astype(np.float32), arr.astype(np.float32))


def test_verify_policy_parsing(monkeypatch):
    monkeypatch.delenv("KT_SHM_VERIFY", raising=False)
    assert shm_ring.verify_policy() == 8
    monkeypatch.setenv("KT_SHM_VERIFY", "all")
    assert shm_ring.verify_policy() == 1
    monkeypatch.setenv("KT_SHM_VERIFY", "0")
    assert shm_ring.verify_policy() == 0
    monkeypatch.setenv("KT_SHM_VERIFY", "32")
    assert shm_ring.verify_policy() == 32
    monkeypatch.setenv("KT_SHM_VERIFY", "junk")
    assert shm_ring.verify_policy() == 8


def test_sampled_verification_covers_first_envelope(ring, monkeypatch):
    monkeypatch.delenv("KT_SHM_VERIFY", raising=False)
    arrs = [np.full(2048, i, dtype=np.float32) for i in range(3)]
    item = {"args": arrs}
    shm_ring.encode_item_fields(item, ring, ("args",), 16, "req")
    hashed = ["hash" in e[SHM_KEY] for e in item["args"]]
    assert hashed[0] is True             # first envelope always verified
    assert hashed[1] is False and hashed[2] is False   # sampled (1/8)


# ---------------------------------------------------------------------------
# Chaos verb: shm-corrupt
# ---------------------------------------------------------------------------


def test_shm_corrupt_parse_and_plan():
    faults = parse_spec("shm-corrupt*2,503")
    assert [f.kind for f in faults] == ["shm-corrupt", "shm-corrupt",
                                       "status"]
    assert shm_corrupt_plan("shm-corrupt*3") == 3
    assert shm_corrupt_plan("reset,503") == 0
    assert shm_corrupt_plan("") == 0


def test_shm_corrupt_invisible_to_http_engine():
    engine = ChaosEngine(parse_spec("shm-corrupt,503"))
    assert len(engine.schedule) == 1 and engine.schedule[0].kind == "status"


def test_shm_corrupt_flips_byte_and_decode_catches(ring, monkeypatch):
    """The full drill at module level: the armed token corrupts the next
    envelope AFTER its hash is recorded, so decode must raise typed."""
    monkeypatch.setenv("KT_CHAOS", "shm-corrupt")
    monkeypatch.setenv("KT_SHM_VERIFY", "0")   # chaos forces the hash anyway
    shm_ring.reset_chaos()
    try:
        arr = np.arange(512, dtype=np.float32)
        item = {"args": [arr]}
        shm_ring.encode_item_fields(item, ring, ("args",), 16, "req")
        assert "hash" in item["args"][0][SHM_KEY]
        with pytest.raises(DataCorruptionError) as ei:
            shm_ring.decode_item_fields(item, ring, ("args",), "req")
        assert ei.value.source == "shm"
    finally:
        shm_ring.reset_chaos()


# ---------------------------------------------------------------------------
# End-to-end through the process pool
# ---------------------------------------------------------------------------


def _run(coro):
    return asyncio.run(coro)


@pytest.mark.slow
def test_pool_shm_roundtrip_byte_exact(monkeypatch):
    monkeypatch.setenv("KT_SHM_THRESHOLD", "65536")
    monkeypatch.setenv("KT_SHM_RING_BYTES", str(8 << 20))
    pool = ProcessPool(1, "spmd", _pointers(), None)
    pool.start()

    async def go():
        a = np.random.default_rng(1).standard_normal(1 << 18).astype(
            np.float32)                  # 1 MB
        b = np.ones(1 << 18, dtype=np.float32)
        out = await pool.call(0, None, [a, b], {}, timeout=90)
        np.testing.assert_array_equal(out, a + b)
        # below-threshold call stays on the queue path, same pool
        assert await pool.call(0, None, [2, 3], {}, timeout=90) == 5

    try:
        _run(go())
        assert pool.workers[0].shm_req is not None
    finally:
        pool.shutdown()
    assert pool.workers[0].shm_req is None      # shutdown reclaimed rings


@pytest.mark.slow
def test_pool_threshold_unset_disables_byte_identically(monkeypatch):
    """KT_SHM_THRESHOLD unset: no segments are created, no envelope
    counters move, and results are identical to the array path."""
    monkeypatch.delenv("KT_SHM_THRESHOLD", raising=False)
    before = _segments()
    pool = ProcessPool(1, "spmd", _pointers(), None)
    pool.start()

    async def go():
        a = np.random.default_rng(2).standard_normal(1 << 17).astype(
            np.float32)
        out = await pool.call(0, None, [a, a], {}, timeout=90)
        np.testing.assert_array_equal(out, a + a)

    try:
        assert pool.workers[0].shm_req is None
        assert pool.workers[0].shm_resp is None
        _run(go())
        assert _segments() == before     # nothing created
    finally:
        pool.shutdown()


@pytest.mark.slow
def test_pool_ring_full_fallback_under_concurrent_large_calls(monkeypatch):
    """A ring far smaller than the traffic: large-array calls race, some
    envelopes fall back inline, every result stays byte-exact."""
    monkeypatch.setenv("KT_SHM_THRESHOLD", "65536")
    monkeypatch.setenv("KT_SHM_RING_BYTES", str(1 << 20))   # 1 MB ring
    pool = ProcessPool(1, "spmd", _pointers(), None)
    pool.start()

    async def go():
        rng = np.random.default_rng(3)
        arrs = [rng.standard_normal(3 << 16).astype(np.float32)  # 768 KB
                for _ in range(6)]
        outs = await asyncio.gather(*[
            pool.call(0, None, [a, a], {}, timeout=120) for a in arrs])
        for a, out in zip(arrs, outs):
            np.testing.assert_array_equal(out, a + a)

    try:
        _run(go())
        from kubetorch_tpu import telemetry
        text = telemetry.REGISTRY.render()
        # the parent encodes 12 arrays of 768KB into a 1MB ring while six
        # calls are in flight: fallbacks are structurally guaranteed
        assert 'kt_shm_ring_fallbacks_total{reason="ring_full"}' in text
    finally:
        pool.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_shm_corrupt_falls_back_to_queue_path(monkeypatch):
    """The acceptance drill: a corrupted envelope must NOT reach the user
    callable — the worker's decode raises typed, the pool retries once
    over the queue path, and the call still returns the right bytes."""
    monkeypatch.setenv("KT_SHM_THRESHOLD", "65536")
    monkeypatch.setenv("KT_CHAOS", "shm-corrupt")
    shm_ring.reset_chaos()
    pool = ProcessPool(1, "spmd", _pointers(), None)
    pool.start()

    async def go():
        a = np.arange(1 << 17, dtype=np.float32)
        out = await pool.call(0, None, [a, a], {}, timeout=90)
        np.testing.assert_array_equal(out, a + a)

    try:
        _run(go())
    finally:
        pool.shutdown()
        shm_ring.reset_chaos()


@pytest.mark.slow
@pytest.mark.chaos
def test_worker_killed_mid_call_leaks_no_segments(monkeypatch):
    """Lifecycle acceptance: kill a rank mid-call (kill-rank chaos), let
    the watchdog restart the pool, and assert the dead generation's
    /dev/shm segments are gone while the fresh generation serves."""
    monkeypatch.setenv("KT_SHM_THRESHOLD", "65536")
    monkeypatch.setenv("KT_CHAOS", "kill-rank:9@0")
    monkeypatch.setenv("KT_WATCHDOG_INTERVAL_S", "0.25")
    monkeypatch.setenv("KT_RESTART_BUDGET", "3")
    monkeypatch.setenv("KT_RESTART_BACKOFF_BASE_S", "0.01")
    monkeypatch.setenv("KT_RESTART_BACKOFF_MAX_S", "0.01")
    pool = ProcessPool(1, "spmd", _pointers(), None)
    pool.start()
    first_gen = {pool.workers[0].shm_req.name, pool.workers[0].shm_resp.name}
    assert first_gen <= _segments()

    async def doomed():
        from kubetorch_tpu.exceptions import WorkerDiedError
        a = np.arange(1 << 17, dtype=np.float32)
        with pytest.raises(WorkerDiedError):
            await pool.call(0, None, [a, a], {}, timeout=30)

    try:
        _run(doomed())
        # watchdog respawns the pool; the dead generation's segments are
        # unlinked by the restart path's cleanup
        assert _wait_until(lambda: not (first_gen & _segments()))
        assert _wait_until(lambda: all(w.alive for w in pool.workers))
        # disarm chaos (the watchdog's replacement inherited the armed
        # env at spawn) and respawn once more: the fresh generation gets
        # fresh rings and serves — the old generation's cleanup already
        # ran through the same force-kill path this exercises again
        monkeypatch.delenv("KT_CHAOS")
        pool.restart_all()

        async def again():
            a = np.arange(1 << 16, dtype=np.float32)
            out = await pool.call(0, None, [a, a], {}, timeout=90)
            np.testing.assert_array_equal(out, a + a)

        _run(again())
        second_gen = {pool.workers[0].shm_req.name,
                      pool.workers[0].shm_resp.name}
        assert second_gen <= _segments() and not (first_gen & second_gen)
    finally:
        pool.shutdown()
    assert not (_segments() & (first_gen | second_gen))
