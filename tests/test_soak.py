"""Chaos-conductor soak harness (ISSUE 15): schedule determinism, the
invariant checkers against hand-built VIOLATING histories, ddmin shrink
convergence, the chaos-verb registry, and the armed-verb counter
composition fix. The slow tier adds a real end-to-end soak plus the
broken-build (ack-before-commit) catch-and-shrink acceptance."""

import json
import os

import pytest

from kubetorch_tpu import chaos
from kubetorch_tpu.soak import (FaultEvent, Schedule, Violation, ddmin,
                                generate)
from kubetorch_tpu.soak import history as H


# ---------------------------------------------------------------------------
# Schedule: seeded generation is byte-identical and replayable
# ---------------------------------------------------------------------------


def test_same_seed_schedule_is_byte_identical():
    # THE determinism anchor: two independent generations from one seed
    # must serialize to the same bytes — replay files depend on it
    for profile in ("store", "train", "serve", "federation", "all"):
        a = generate(42, profile, 60).to_json()
        b = generate(42, profile, 60).to_json()
        assert a == b
        assert a.encode() == b.encode()


def test_different_seed_changes_the_schedule():
    assert generate(1, "all", 60).to_json() != generate(2, "all", 60).to_json()


def test_schedule_roundtrips_through_json():
    sched = generate(7, "all", 40)
    back = Schedule.from_json(sched.to_json())
    assert back.to_json() == sched.to_json()
    assert back.events == sorted(sched.events,
                                 key=lambda e: (e.at_op, e.action, e.target))


def test_store_death_windows_are_disjoint():
    # a 3-node R=2/W=2 ring tolerates exactly one dead member: overlapping
    # death windows would schedule quorum loss instead of finding bugs
    for seed in range(30):
        sched = generate(seed, "store", 90)
        open_kills = 0
        timeline = []
        for t, tok in sched.boot_chaos.items():
            if "kill-store-node" in tok:
                idx = int(tok.split("@")[-1])
                timeline.append((idx, "kill"))
        for e in sched.events:
            if e.action in ("kill-node", "restart-node"):
                timeline.append((e.at_op, e.action.split("-")[0]))
        peak = 0
        for _, what in sorted(timeline):
            open_kills += 1 if what == "kill" else -1
            peak = max(peak, open_kills)
        assert peak <= 1, f"seed {seed}: {peak} simultaneous node deaths"


def test_generate_rejects_unknown_profile():
    with pytest.raises(ValueError):
        generate(1, "nope", 10)


def test_persistent_boot_verbs_are_retryable_only():
    # corrupt-blob / disk-full / torn-write poison the settle verify-reads;
    # only client-absorbable verbs may arm persistently
    safe = {"delay", "status", "reset", "shed", "oom", "evict", "preempt",
            "kill-store-node"}
    for seed in range(20):
        sched = generate(seed, "all", 60)
        for token in sched.boot_chaos.values():
            for part in token.split(","):
                for f in chaos.parse_spec(part):
                    assert f.kind in safe, f"{f.kind} armed at boot"


# ---------------------------------------------------------------------------
# Invariant checkers, each fed a hand-built VIOLATING history
# ---------------------------------------------------------------------------


def _op(i, op, key, ok=True, typed=True, acked=None, error=""):
    r = {"kind": "op", "op": op, "key": key, "ok": ok, "typed": typed,
         "index": i}
    if acked is not None:
        r["acked"] = acked
    if error:
        r["error"] = error
    return r


def test_durability_catches_a_lost_acked_write():
    records = [
        _op(0, "put", "soak/k1", acked=True),
        {"kind": "verify", "key": "soak/k1", "ok": False, "match": False,
         "index": 1},
    ]
    out = H.check_durability(records)
    assert len(out) == 1 and out[0].invariant == "durability"
    assert "unreadable" in out[0].detail


def test_durability_catches_a_content_mismatch_and_missing_verify():
    records = [
        _op(0, "put", "soak/k1", acked=True),
        _op(1, "put", "soak/k2", acked=True),
        {"kind": "verify", "key": "soak/k1", "ok": True, "match": False,
         "index": 2},
        # k2 was never verified — silently skipping the read-back is
        # itself a violation
    ]
    got = {v.detail.split("'")[1]: v for v in H.check_durability(records)}
    assert "mismatch" in got["soak/k1"].detail
    assert "never verified" in got["soak/k2"].detail


def test_durability_released_by_rm_and_green_path():
    records = [
        _op(0, "put", "soak/k1", acked=True),
        _op(1, "rm", "soak/k1"),
        _op(2, "put", "soak/k2", acked=True),
        {"kind": "verify", "key": "soak/k2", "ok": True, "match": True,
         "index": 3},
    ]
    assert H.check_durability(records) == []


def test_commits_catches_a_lost_committed_step():
    records = [
        {"kind": "trainer", "event": "committed", "step": 5,
         "fingerprint": "aaa", "index": 0},
        {"kind": "trainer", "event": "restored", "step": 3,
         "fingerprint": "bbb", "index": 1},
    ]
    out = H.check_commits(records)
    assert any(v.invariant == "commit-monotonic" for v in out)


def test_commits_catches_restore_from_scratch_after_commits():
    records = [
        {"kind": "trainer", "event": "committed", "step": 2,
         "fingerprint": "aaa", "index": 0},
        {"kind": "trainer", "event": "restored", "step": None, "index": 1},
    ]
    out = H.check_commits(records)
    assert any("from scratch" in v.detail for v in out)


def test_commits_catches_a_fingerprint_mismatch():
    records = [
        {"kind": "trainer", "event": "committed", "step": 4,
         "fingerprint": "aaaaaaaaaaaaaaaa", "index": 0},
        {"kind": "trainer", "event": "restored", "step": 4,
         "fingerprint": "bbbbbbbbbbbbbbbb", "index": 1},
    ]
    out = H.check_commits(records)
    assert any(v.invariant == "commit-fingerprint" for v in out)


def test_commits_green_path():
    records = [
        {"kind": "trainer", "event": "committed", "step": 1,
         "fingerprint": "a1", "index": 0},
        {"kind": "trainer", "event": "committed", "step": 2,
         "fingerprint": "a2", "index": 1},
        {"kind": "trainer", "event": "restored", "step": 2,
         "fingerprint": "a2", "index": 2},
        {"kind": "trainer", "event": "committed", "step": 3,
         "fingerprint": "a3", "index": 3},
    ]
    assert H.check_commits(records) == []


def test_lease_fencing_catches_a_stale_epoch_placement():
    records = [
        {"kind": "lease", "event": "grant", "workload": "j", "region": "a",
         "epoch": 1, "index": 0},
        {"kind": "placement", "event": "start", "workload": "j",
         "region": "a", "epoch": 1, "index": 1},
        {"kind": "lease", "event": "grant", "workload": "j", "region": "b",
         "epoch": 2, "index": 2},
        # the fenced region keeps heartbeating at its old epoch
        {"kind": "placement", "event": "confirmed", "workload": "j",
         "region": "a", "epoch": 1, "index": 3},
    ]
    out = H.check_lease_fencing(records)
    assert any("stale epoch" in v.detail for v in out)


def test_lease_fencing_catches_a_double_placement():
    records = [
        {"kind": "lease", "event": "grant", "workload": "j", "region": "a",
         "epoch": 1, "index": 0},
        {"kind": "placement", "event": "start", "workload": "j",
         "region": "a", "epoch": 1, "index": 1},
        {"kind": "lease", "event": "grant", "workload": "j", "region": "b",
         "epoch": 2, "index": 2},
        # region-b starts WITHOUT region-a ever stopping: split brain
        {"kind": "placement", "event": "start", "workload": "j",
         "region": "b", "epoch": 2, "index": 3},
    ]
    out = H.check_lease_fencing(records)
    assert any("BOTH" in v.detail for v in out)


def test_lease_fencing_green_failover():
    records = [
        {"kind": "lease", "event": "grant", "workload": "j", "region": "a",
         "epoch": 1, "index": 0},
        {"kind": "placement", "event": "start", "workload": "j",
         "region": "a", "epoch": 1, "index": 1},
        {"kind": "lease", "event": "grant", "workload": "j", "region": "b",
         "epoch": 2, "index": 2},
        {"kind": "placement", "event": "stop", "workload": "j",
         "region": "a", "epoch": 1, "index": 3},
        {"kind": "placement", "event": "start", "workload": "j",
         "region": "b", "epoch": 2, "index": 4},
    ]
    assert H.check_lease_fencing(records) == []


def test_typed_errors_catches_a_raw_escape():
    records = [
        _op(0, "get", "soak/k1", ok=False, typed=False,
            error="ConnectionError"),
        _op(1, "get", "soak/k2", ok=False, typed=True,
            error="DataStoreError"),
    ]
    out = H.check_typed_errors(records)
    assert len(out) == 1
    assert "ConnectionError" in out[0].detail


def test_ring_convergence_catches_a_degraded_final_state():
    records = [
        _op(0, "put", "soak/k1", acked=True),
        {"kind": "ring-status", "under_replicated": 3, "nodes_down": 1,
         "index": 1},
    ]
    out = H.check_ring_converged(records)
    assert len(out) == 1 and "did not re-converge" in out[0].detail


def test_ring_convergence_requires_a_verdict_when_store_ops_ran():
    out = H.check_ring_converged([_op(0, "put", "soak/k1", acked=True)])
    assert len(out) == 1 and "no final ring-status" in out[0].detail


def test_no_leaks_catches_shm_and_tmp():
    records = [{"kind": "leak-scan", "shm": ["kt-ring-1"],
                "tmp": ["kv/x.tmp"], "index": 0}]
    out = H.check_no_leaks(records)
    assert {v.detail.split(":")[0] for v in out} == \
        {"leaked /dev/shm segments", "orphan .tmp files"}


def test_check_all_runs_every_invariant():
    assert set(H.INVARIANTS) == {"durability", "commits", "lease-fencing",
                                 "typed-errors", "ring-convergence",
                                 "no-leaks", "pipeline-progress",
                                 "flywheel-ledger", "blackbox"}
    assert H.check_all([]) == []


def test_classify_error_typed_vs_raw():
    from kubetorch_tpu.exceptions import DataStoreError
    name, typed = H.classify_error(DataStoreError("x"))
    assert name == "DataStoreError" and typed
    name, typed = H.classify_error(ConnectionError("x"))
    assert name == "ConnectionError" and not typed


def test_violation_serializes():
    v = Violation("durability", "d", [1, 2])
    assert json.loads(json.dumps(v.to_dict())) == {
        "invariant": "durability", "detail": "d", "records": [1, 2]}


# ---------------------------------------------------------------------------
# Shrink: ddmin converges to the known-minimal core
# ---------------------------------------------------------------------------


def test_ddmin_converges_to_the_minimal_core():
    items = [f"E{i}" for i in range(12)]
    core = {"E2", "E5"}
    calls = []

    def violates(subset):
        calls.append(len(subset))
        return core <= set(subset)

    out = ddmin(items, violates)
    assert set(out) == core
    # order preserved from the original list
    assert out == ["E2", "E5"]


def test_ddmin_single_element_core():
    items = list(range(9))
    assert ddmin(items, lambda s: 7 in s) == [7]


def test_ddmin_full_set_needed_stays_full():
    items = [1, 2, 3]
    assert ddmin(items, lambda s: len(s) == 3) == [1, 2, 3]


def test_ddmin_rejects_a_non_violating_input():
    with pytest.raises(ValueError):
        ddmin([1, 2], lambda s: False)


def test_ddmin_respects_the_test_budget():
    items = list(range(64))
    calls = [0]

    def violates(subset):
        calls[0] += 1
        return {3, 40} <= set(subset)

    out = ddmin(items, violates, max_tests=5)
    # capped: still a valid repro (contains the core), maybe not minimal
    assert {3, 40} <= set(out)
    assert calls[0] <= 5


# ---------------------------------------------------------------------------
# Chaos-verb registry (ISSUE 15 satellite) + counter composition fix
# ---------------------------------------------------------------------------


def test_registry_covers_every_parser_kind():
    names = {v.name for v in chaos.verb_registry()}
    assert names == set(chaos._KINDS)


def test_registry_examples_parse():
    for v in chaos.verb_registry():
        faults = chaos.parse_spec(v.example)
        assert faults, f"example for {v.name} parsed to nothing"


def test_registry_dicts_are_json_clean():
    dicts = chaos.registry_as_dicts()
    json.dumps(dicts)
    assert all(set(d) >= {"name", "scope", "grammar", "consumer",
                          "summary", "example"} for d in dicts)


def test_grammar_markdown_names_every_verb():
    md = chaos.grammar_markdown()
    for v in chaos.verb_registry():
        assert f"`{v.name}`" in md


def test_armed_verb_classes_compose_without_counter_skew():
    # the ISSUE 15 composition fix: a kill-peer firing at op 1 must NOT
    # shift kill-store-node@2 to the 3rd op (the old shared-counter race)
    eng = chaos.ChaosEngine(
        chaos.parse_spec("kill-peer@1,kill-store-node@2"))
    hits = [eng.next_fault("/kv/x", method="GET") for _ in range(4)]
    assert [h.kind if h else None for h in hits] == \
        [None, "kill-peer", "kill-store-node", None]


def test_node_fault_firing_does_not_starve_region_fault(monkeypatch):
    monkeypatch.setenv("KT_REGION", "r")
    eng = chaos.ChaosEngine(
        chaos.parse_spec("kill-store-node@1,kill-region:1@r"))
    kinds = [f.kind if f else None
             for f in (eng.next_fault("/kv/x", method="PUT")
                       for _ in range(3))]
    # both op-indexed classes advance every op: the node kill fires at its
    # index and the region kill fires at-or-after its own, never never
    assert "kill-store-node" in kinds and "kill-region" in kinds


def test_pop_due_fires_at_or_after_a_missed_index():
    eng = chaos.ChaosEngine(chaos.parse_spec("kill-store-node@0"))
    # exempt paths don't advance the counters; the op-indexed kill still
    # fires on the first qualifying op instead of being silently dropped
    assert eng.next_fault("/health", method="GET") is None
    hit = eng.next_fault("/kv/x", method="PUT")
    assert hit is not None and hit.kind == "kill-store-node"


# ---------------------------------------------------------------------------
# Docs drift: the resilience runbook embeds the generated grammar
# ---------------------------------------------------------------------------


def test_resilience_docs_embed_the_registry_grammar():
    doc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "resilience.md")
    text = open(doc).read()
    for line in chaos.grammar_markdown().splitlines():
        assert line in text, f"docs/resilience.md drifted: missing {line!r}"


# ---------------------------------------------------------------------------
# End-to-end (slow tier): a real conducted soak + the broken-build catch
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_store_soak_runs_green(tmp_path):
    from kubetorch_tpu.soak.conductor import run_soak

    sched = generate(7, "store", 16)
    res = run_soak(sched, str(tmp_path), op_interval_s=0.1,
                   settle_timeout_s=45)
    assert res.ok, [v.to_dict() for v in res.violations]
    # not trivially green: real acked writes happened and were verified
    assert any(r["kind"] == "op" and r["op"] == "put" and r["ok"]
               for r in res.records)
    assert any(r["kind"] == "verify" for r in res.records)


@pytest.mark.slow
@pytest.mark.chaos
def test_broken_build_is_caught_and_shrinks_to_a_minimal_repro(
        tmp_path, monkeypatch):
    """THE acceptance scenario: a store that acks before its durable
    commit (KT_SOAK_BREAK=ack-before-commit) must be caught by the
    durability invariant, shrink to <=3 events, and refire on replay."""
    from kubetorch_tpu.soak.conductor import (load_replay, run_soak,
                                              shrink_violation,
                                              write_replay)

    monkeypatch.setenv("KT_SOAK_BREAK", "ack-before-commit")
    monkeypatch.setenv("KT_SOAK_BREAK_DELAY_S", "1.0")
    sched = Schedule(
        seed=11, profile="store", n_ops=12, store_nodes=3,
        events=[FaultEvent(6, "kill-node", "store:0"),
                FaultEvent(6, "kill-node", "store:1"),
                FaultEvent(9, "restart-node", "store:0"),
                FaultEvent(9, "restart-node", "store:1")])
    res = run_soak(sched, str(tmp_path), op_interval_s=0.1,
                   settle_timeout_s=45)
    assert any(v.invariant == "durability" for v in res.violations), \
        "the deliberately broken build was not caught"

    mini = shrink_violation(sched, str(tmp_path), "durability",
                            op_interval_s=0.1, settle_timeout_s=45)
    assert len(mini.events) <= 3

    replay_path = str(tmp_path / "repro.json")
    write_replay(mini, replay_path, res.violations)
    again = load_replay(replay_path)
    res2 = run_soak(again, str(tmp_path / "refire"), op_interval_s=0.1,
                    settle_timeout_s=45, events_override=again.events)
    assert any(v.invariant == "durability" for v in res2.violations), \
        "the shrunk repro did not refire"
