"""Speculative continuous batching (serve/spec_engine.py).

The contract is the intersection of its parents': like the engine, every
request's greedy tokens must match a solo ``generate`` run WHATEVER the
slot neighbors do; like standalone speculation, that must hold for any
draft, with acceptance only short-cutting identical outcomes. MoE targets
hold the same bar (drop-free verify windows).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubetorch_tpu.models.generate import generate
from kubetorch_tpu.models.llama import LlamaConfig, llama_init
from kubetorch_tpu.serve.spec_engine import SpeculativeEngine

pytestmark = [pytest.mark.level("unit"), pytest.mark.slow]


@pytest.fixture(scope="module")
def models():
    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    target = llama_init(jax.random.PRNGKey(0), cfg)
    dcfg = LlamaConfig.tiny(dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
                            ffn_dim=64, attn_impl="xla", dtype=jnp.float32,
                            remat=False)
    draft = llama_init(jax.random.PRNGKey(7), dcfg)
    return target, cfg, draft, dcfg


def _solo(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _drain(eng):
    while eng.step():
        pass


class TestExactness:
    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_concurrent_requests_match_solo_generate(self, models, k):
        target, cfg, draft, dcfg = models
        prompts = [[5, 17, 42], [100, 200, 300, 400, 401], [1, 2]]
        ns = [8, 11, 5]
        want = [_solo(target, cfg, p, n) for p, n in zip(prompts, ns)]
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=k,
                                slots=4, max_len=64, prefill_buckets=(8,))
        handles = [eng.submit(p, max_new_tokens=n)
                   for p, n in zip(prompts, ns)]
        _drain(eng)
        got = [h.result(timeout=0) for h in handles]
        assert got == want
        assert eng.spec_stats.rounds >= 1

    def test_self_draft_accepts_everything(self, models):
        """Draft == target: 100% acceptance, and the whole grid advances
        ~k+1 tokens per slot per round."""
        target, cfg, _, _ = models
        prompts = [[3, 4, 5], [9, 8, 7]]
        want = [_solo(target, cfg, p, 12) for p in prompts]
        eng = SpeculativeEngine(target, cfg, target, cfg, spec_k=3,
                                slots=2, max_len=64, prefill_buckets=(8,))
        handles = [eng.submit(p, max_new_tokens=12) for p in prompts]
        _drain(eng)
        assert [h.result(timeout=0) for h in handles] == want
        assert eng.spec_stats.acceptance_rate == 1.0
        # 12 tokens = 1 (prefill) + rounds*(k+1=4): ceil(11/4)=3 per slot
        assert eng.spec_stats.rounds <= 2 * 3 + 1

    def test_mid_flight_admission(self, models):
        """A request admitted while neighbors are mid-speculation must not
        perturb them — and must itself be exact."""
        target, cfg, draft, dcfg = models
        pa, pb = [5, 17, 42, 99], [7, 7, 7]
        want_a = _solo(target, cfg, pa, 10)
        want_b = _solo(target, cfg, pb, 6)
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=3,
                                slots=2, max_len=64, prefill_buckets=(8,))
        ha = eng.submit(pa, max_new_tokens=10)
        eng.step()
        eng.step()
        hb = eng.submit(pb, max_new_tokens=6)       # joins mid-flight
        _drain(eng)
        assert ha.result(timeout=0) == want_a
        assert hb.result(timeout=0) == want_b

    def test_slot_reuse_after_retirement(self, models):
        target, cfg, draft, dcfg = models
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                                slots=1, max_len=64, prefill_buckets=(8,))
        for prompt, n in [([5, 17], 5), ([42, 43, 44], 7), ([1], 4)]:
            want = _solo(target, cfg, prompt, n)
            h = eng.submit(prompt, max_new_tokens=n)
            _drain(eng)
            assert h.result(timeout=0) == want, (prompt, n)

    def test_eos_retires_early(self, models):
        target, cfg, draft, dcfg = models
        ref = _solo(target, cfg, [5, 17, 42], 12)
        eos = ref[4]                                 # retire mid-stream
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=3,
                                slots=2, max_len=64, prefill_buckets=(8,),
                                eos_id=eos)
        h = eng.submit([5, 17, 42], max_new_tokens=12)
        _drain(eng)
        got = h.result(timeout=0)
        assert got == ref[:5]                        # up to AND incl. eos
        # the slot is free again
        h2 = eng.submit([9, 8], max_new_tokens=3)
        _drain(eng)
        assert len(h2.result(timeout=0)) == 3


class TestMoeTarget:
    def test_moe_target_exact(self, models):
        from kubetorch_tpu.models.moe import MoeConfig, moe_init
        _, _, draft, dcfg = models
        mcfg = MoeConfig.tiny(dtype=jnp.float32, remat=False,
                              attn_impl="xla")
        mo = moe_init(jax.random.PRNGKey(1), mcfg)
        prompts = [[5, 17, 42, 99], [7] * 6]
        ns = [9, 7]
        want = [_solo(mo, mcfg, p, n) for p, n in zip(prompts, ns)]
        eng = SpeculativeEngine(mo, mcfg, draft, dcfg, spec_k=3,
                                slots=2, max_len=64, prefill_buckets=(8,))
        handles = [eng.submit(p, max_new_tokens=n)
                   for p, n in zip(prompts, ns)]
        _drain(eng)
        assert [h.result(timeout=0) for h in handles] == want


class TestValidation:
    def test_refusals(self, models):
        target, cfg, draft, dcfg = models
        with pytest.raises(ValueError, match="greedy-only"):
            SpeculativeEngine(target, cfg, draft, dcfg, temperature=0.7,
                              max_len=64)
        # quantize_kv is SUPPORTED now (TestInt8KvCache) — no refusal
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                                slots=2, max_len=32, prefill_buckets=(8,))
        with pytest.raises(ValueError, match="greedy-only"):
            eng.submit([1, 2], max_new_tokens=3, temperature=0.5)
        with pytest.raises(KeyError, match="prefix"):
            eng.submit([1, 2], max_new_tokens=3, prefix_id=99)
        with pytest.raises(ValueError, match="verify window"):
            # 8 + 20 + 5 > 32: the verify window headroom must be reserved
            eng.submit([1] * 8, max_new_tokens=20)
        # prefixes and adapters are both SUPPORTED now (TestPrefixCache,
        # TestMultiLora) — an unknown id is the only registration error

    def test_background_loop(self, models):
        target, cfg, draft, dcfg = models
        want = _solo(target, cfg, [5, 6, 7], 8)
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                                slots=2, max_len=64, prefill_buckets=(8,))
        try:
            got = eng.generate([5, 6, 7], 8, timeout=300)
        finally:
            eng.stop()
        assert got == want


class TestFuzz:
    def test_randomized_interleavings_match_solo(self, models):
        """Random prompts/lengths/budgets/k, submissions staggered across
        running rounds — every request must still equal its solo run.
        Catches ledger bugs no hand-written interleaving thinks of."""
        import random

        target, cfg, draft, dcfg = models
        rng = random.Random(0xC0FFEE)
        for trial in range(3):
            k = rng.choice([1, 2, 3, 4])
            slots = rng.choice([1, 2, 3])
            eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=k,
                                    slots=slots, max_len=64,
                                    prefill_buckets=(4, 8))
            reqs = []
            n_reqs = rng.randint(2, 5)
            for _ in range(n_reqs):
                prompt = [rng.randrange(cfg.vocab_size)
                          for _ in range(rng.randint(1, 10))]
                n = rng.randint(1, 12)
                reqs.append((prompt, n))
            handles = []
            it = iter(reqs)
            # stagger submissions between rounds
            for prompt, n in [next(it)]:
                handles.append(eng.submit(prompt, max_new_tokens=n))
            for prompt, n in it:
                eng.step()
                handles.append(eng.submit(prompt, max_new_tokens=n))
            _drain(eng)
            for (prompt, n), h in zip(reqs, handles):
                want = _solo(target, cfg, prompt, n)
                assert h.result(timeout=0) == want, (trial, k, slots,
                                                     prompt, n)


class TestInt8KvCache:
    """quantize_kv composes with speculation: the TARGET cache quantizes
    (rows quantized at write, scales folded into the verify-window
    attention), the draft stays fp. Oracle: the plain engine with the
    same int8 cache — emitted streams must be bit-equal, since both run
    the reference quant math over identical row values."""

    def test_bit_equal_to_plain_quant_engine(self):
        from kubetorch_tpu.models.llama import LlamaConfig, llama_init
        from kubetorch_tpu.serve import GenerationEngine
        from kubetorch_tpu.serve.spec_engine import SpeculativeEngine
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        target = llama_init(jax.random.PRNGKey(0), cfg)
        draft = llama_init(jax.random.PRNGKey(1), cfg)

        def plain(prompt, n):
            eng = GenerationEngine(target, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4, 8),
                                   quantize_kv=True)
            h = eng.submit(prompt, max_new_tokens=n)
            while eng.step():
                pass
            return h.result(timeout=0)

        spec = SpeculativeEngine(target, cfg, draft, cfg, spec_k=3,
                                 slots=2, max_len=64,
                                 prefill_buckets=(4, 8), quantize_kv=True)
        prompts = [[5, 17, 42], [1, 2]]
        hs = [spec.submit(p, max_new_tokens=8) for p in prompts]
        while spec.step():
            pass
        for h, p in zip(hs, prompts):
            assert h.result(timeout=0) == plain(p, 8), p
        assert spec.spec_stats.rounds > 0


class TestMultiLora:
    """Adapters compose with speculation: the target's window forwards
    gather each slot's adapter (bank index 0 = base), the draft proposes
    from its own base weights (proposal quality only — never tokens).
    Oracle: the plain engine running the same adapter."""

    def test_adapter_beside_base_exact(self):
        from kubetorch_tpu.models.llama import LlamaConfig, llama_init
        from kubetorch_tpu.models.lora import LoraConfig, lora_init
        from kubetorch_tpu.serve import GenerationEngine
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32,
                               remat=False)
        target = llama_init(jax.random.PRNGKey(0), cfg)
        draft = llama_init(jax.random.PRNGKey(1), cfg)
        lcfg = LoraConfig(rank=4)
        ad = lora_init(jax.random.PRNGKey(7), target, lcfg)
        keys = jax.random.split(jax.random.PRNGKey(1007),
                                len(ad["layers"]))
        ad["layers"] = {
            k: (v if k.endswith("__a")
                else jax.random.normal(kk, v.shape, v.dtype) * 0.05)
            for kk, (k, v) in zip(keys, sorted(ad["layers"].items()))}

        def plain(prompt, n, adapters=None):
            eng = GenerationEngine(target, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4, 8))
            kw = {}
            if adapters is not None:
                kw["adapter_id"] = eng.register_adapter(adapters, lcfg)
            h = eng.submit(prompt, max_new_tokens=n, **kw)
            while eng.step():
                pass
            return h.result(timeout=0)

        spec = SpeculativeEngine(target, cfg, draft, cfg, spec_k=3,
                                 slots=2, max_len=64,
                                 prefill_buckets=(4, 8))
        aid = spec.register_adapter(ad, lcfg)
        h_a = spec.submit([5, 17, 42], max_new_tokens=8, adapter_id=aid)
        h_b = spec.submit([1, 2], max_new_tokens=6)      # base neighbor
        while spec.step():
            pass
        assert h_a.result(timeout=0) == plain([5, 17, 42], 8, ad)
        assert h_b.result(timeout=0) == plain([1, 2], 6)
        # the adapter genuinely changes the stream
        assert h_a.result(timeout=0) != plain([5, 17, 42], 8)
        # eviction repoints at base without recompiling
        assert spec.unregister_adapter(aid) is True
        h_c = spec.submit([5, 17, 42], max_new_tokens=4)
        while spec.step():
            pass
        assert h_c.result(timeout=0) == plain([5, 17, 42], 4)


class TestPrefixCache:
    """Prefix caching under speculation: both models splice their own
    cached prefix at admission (same bucket widths), and the emitted
    stream equals the plain engine's prefix run AND the full-prompt solo
    run — exact for dense models."""

    def test_prefix_matches_plain_and_full(self, models):
        from kubetorch_tpu.serve import GenerationEngine
        target, cfg, draft, dcfg = models
        prefix, suffix = [5, 17, 42], [9, 11]

        def plain(n, use_prefix):
            eng = GenerationEngine(target, cfg, slots=1, max_len=64,
                                   prefill_buckets=(4, 8))
            kw, p = {}, prefix + suffix
            if use_prefix:
                kw["prefix_id"] = eng.register_prefix(prefix)
                p = suffix
            h = eng.submit(p, max_new_tokens=n, **kw)
            while eng.step():
                pass
            return h.result(timeout=0)

        spec = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=3,
                                 slots=2, max_len=64,
                                 prefill_buckets=(4, 8))
        pid = spec.register_prefix(prefix)
        h = spec.submit(suffix, max_new_tokens=8, prefix_id=pid)
        h2 = spec.submit([1, 2], max_new_tokens=5)
        _drain(spec)
        assert h.result(timeout=0) == plain(8, True) == plain(8, False)
        assert len(h2.result(timeout=0)) == 5
        # eviction clears BOTH models' cached prefixes
        assert spec.unregister_prefix(pid)
        assert pid not in spec._draft_prefixes
        # verify-window headroom accounts for the prefix bucket
        pid2 = spec.register_prefix([1] * 8)
        with pytest.raises(ValueError, match="verify window"):
            spec.submit([2] * 8, max_new_tokens=48, prefix_id=pid2)

    def test_registration_validation_and_auto_prefix_refusal(self, models):
        target, cfg, draft, dcfg = models
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                                slots=1, max_len=32, prefill_buckets=(8,))
        with pytest.raises(ValueError, match="empty"):
            eng.register_prefix([])
        with pytest.raises(ValueError, match="max_len"):
            eng.register_prefix([1] * 32)
        with pytest.raises(ValueError, match="auto_prefix"):
            SpeculativeEngine(target, cfg, draft, dcfg, max_len=32,
                              auto_prefix=True)


class TestShardedSpec:
    def test_spec_engine_matches_under_tensor_sharded_mesh(
            self, cpu_mesh_devices, models):
        """The claim 'tensor/data meshes work GSPMD-sharded like the
        plain engine' as an assertion: sharded target+draft params on a
        data×tensor mesh, greedy tokens unchanged vs the solo run."""
        from kubetorch_tpu.parallel.mesh import build_mesh
        from kubetorch_tpu.parallel.mesh_context import use_mesh
        from kubetorch_tpu.parallel.sharding import (LLAMA_RULES,
                                                     shard_pytree)
        target, cfg, draft, dcfg = models
        prompts = [[5, 17, 42], [9, 9, 9, 9]]
        want = [_solo(target, cfg, p, 6) for p in prompts]
        mesh = build_mesh({"data": 2, "tensor": 2},
                          devices=cpu_mesh_devices[:4])
        st = shard_pytree(target, LLAMA_RULES, mesh)
        sd = shard_pytree(draft, LLAMA_RULES, mesh)
        with use_mesh(mesh):
            eng = SpeculativeEngine(st, cfg, sd, dcfg, spec_k=2, slots=4,
                                    max_len=64, prefill_buckets=(8,))
            handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
            _drain(eng)
        for h, w in zip(handles, want):
            assert h.result(timeout=0) == w


class TestChunkedPrefill:
    """prefill_chunk under speculation: BOTH models' accumulators advance
    one chunk per engine step; the emitted stream equals the plain
    engine's decode of the same prompt."""

    def test_long_prompt_chunked_exact(self, models):
        target, cfg, draft, dcfg = models
        long_prompt = list(range(5, 16))
        want = _solo(target, cfg, long_prompt, 8)
        spec = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=3,
                                 slots=2, max_len=64,
                                 prefill_buckets=(4, 16), prefill_chunk=4)
        h = spec.submit(long_prompt, max_new_tokens=8)
        h2 = spec.submit([1, 2], max_new_tokens=5)       # short neighbor
        _drain(spec)
        assert h.result(timeout=0) == want
        assert len(h2.result(timeout=0)) == 5

    def test_chunked_behind_prefix(self, models):
        target, cfg, draft, dcfg = models
        prefix = [5, 17, 42]
        suffix = list(range(30, 39))
        want = _solo(target, cfg, prefix + suffix, 5)
        spec = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                                 slots=1, max_len=64,
                                 prefill_buckets=(4, 8), prefill_chunk=4)
        pid = spec.register_prefix(prefix)
        h = spec.submit(suffix, max_new_tokens=5, prefix_id=pid)
        _drain(spec)
        assert h.result(timeout=0) == want

    def test_cancel_mid_chunking(self, models):
        target, cfg, draft, dcfg = models
        spec = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                                 slots=2, max_len=64,
                                 prefill_buckets=(4, 16), prefill_chunk=4)
        h = spec.submit(list(range(5, 16)), max_new_tokens=6)
        spec.step()
        assert h.cancel() is True
        _drain(spec)
        assert h.result(timeout=0) == []
        w2 = _solo(target, cfg, [9], 3)
        h2 = spec.submit([9], max_new_tokens=3)
        _drain(spec)
        assert h2.result(timeout=0) == w2


class TestAdaptiveDraftLength:
    """Acceptance-rate EWMA → draft length (ISSUE 12 satellite): k grows
    while the draft earns its windows, shrinks when it doesn't, stays
    static when the bounds are not widened — and exactness holds at every
    k along the way (greedy verification is k-independent)."""

    def test_bounds_default_to_static(self, models):
        target, cfg, draft, dcfg = models
        eng = SpeculativeEngine(target, cfg, target, cfg, spec_k=3,
                                slots=1, max_len=64, prefill_buckets=(8,),
                                spec_adapt_every=1)
        h = eng.submit([3, 4, 5], max_new_tokens=16)
        _drain(eng)
        h.result(timeout=0)
        assert eng.k == eng.k_min == eng.k_max == 3   # adaptation off

    def test_perfect_draft_grows_k(self, models):
        target, cfg, _, _ = models
        prompt, n = [3, 4, 5], 24
        want = _solo(target, cfg, prompt, n)
        eng = SpeculativeEngine(target, cfg, target, cfg, spec_k=2,
                                spec_k_min=1, spec_k_max=4,
                                spec_adapt_every=1, slots=1, max_len=128,
                                prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=n)
        _drain(eng)
        assert h.result(timeout=0) == want            # exact at every k
        assert eng.k == 4, "self-draft (EWMA 1.0) must grow to k_max"

    def test_bad_draft_shrinks_k(self, models):
        target, cfg, draft, dcfg = models
        prompt, n = [7, 8, 9], 24
        want = _solo(target, cfg, prompt, n)
        # the random tiny draft agrees with the target ~never (1/512)
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=3,
                                spec_k_min=1, spec_k_max=3,
                                spec_adapt_every=1, slots=1, max_len=128,
                                prefill_buckets=(8,))
        h = eng.submit(prompt, max_new_tokens=n)
        _drain(eng)
        assert h.result(timeout=0) == want
        assert eng.k == 1, "near-zero acceptance must shrink to k_min"

    def test_env_bounds_and_gauges(self, models, monkeypatch):
        from kubetorch_tpu import telemetry

        target, cfg, _, _ = models
        monkeypatch.setenv("KT_SPEC_K_MIN", "1")
        monkeypatch.setenv("KT_SPEC_K_MAX", "5")
        eng = SpeculativeEngine(target, cfg, target, cfg, spec_k=2,
                                spec_adapt_every=1, slots=1, max_len=128,
                                prefill_buckets=(8,))
        assert (eng.k_min, eng.k_max) == (1, 5)
        h = eng.submit([1, 2], max_new_tokens=12)
        _drain(eng)
        h.result(timeout=0)
        gauges = telemetry.spec_metrics()
        assert gauges["draft_len"].value() == eng.k
        assert gauges["accept_rate"].value() > 0.9    # self-draft
        # __kt_metrics__ exports the adaptive k for the pod scrape
        assert eng.__kt_metrics__()["engine_spec_draft_len"] == float(eng.k)

    def test_invalid_bounds_refused(self, models):
        target, cfg, draft, dcfg = models
        with pytest.raises(ValueError, match="k_min"):
            SpeculativeEngine(target, cfg, draft, dcfg, spec_k=2,
                              spec_k_min=3, spec_k_max=4, slots=1,
                              max_len=64)

    def test_headroom_reserved_for_k_max(self, models):
        """submit() must reserve the verify window of the LARGEST k
        adaptation may pick, so a later grow can't scatter out of
        bounds."""
        target, cfg, draft, dcfg = models
        eng = SpeculativeEngine(target, cfg, draft, dcfg, spec_k=1,
                                spec_k_min=1, spec_k_max=8, slots=1,
                                max_len=32, prefill_buckets=(8,))
        with pytest.raises(ValueError, match="verify window"):
            eng.submit([1, 2, 3], max_new_tokens=15)  # 3+15+(2*8+1) > 32
