"""Speculative decoding (serve/speculative.py).

The oracle: greedy speculative output is BIT-IDENTICAL to plain greedy
``generate`` of the target, whatever the draft proposes — acceptance only
shortcuts identical outcomes. Any position-ledger or cache-invariant bug
breaks this equality immediately, so it is the whole contract.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubetorch_tpu.models.generate import generate
from kubetorch_tpu.models.llama import LlamaConfig, llama_init
from kubetorch_tpu.serve import SpecStats, speculative_generate

pytestmark = [pytest.mark.level("unit"), pytest.mark.slow]


@pytest.fixture(scope="module")
def models():
    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    target = llama_init(jax.random.PRNGKey(0), cfg)
    # a smaller, differently-seeded draft: same vocab, fewer layers/dims
    dcfg = LlamaConfig.tiny(dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
                            ffn_dim=64, attn_impl="xla", dtype=jnp.float32,
                            remat=False)
    draft = llama_init(jax.random.PRNGKey(7), dcfg)
    return target, cfg, draft, dcfg


def _solo(params, cfg, prompt, n):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):].tolist()


class TestExactness:
    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_matches_target_greedy_for_any_draft(self, models, k):
        target, cfg, draft, dcfg = models
        prompt = [5, 17, 42, 99]
        want = _solo(target, cfg, prompt, 12)
        stats = SpecStats()
        got = speculative_generate(target, cfg, draft, dcfg, prompt,
                                   max_new_tokens=12, k=k, stats=stats)
        assert got == want
        assert stats.rounds >= 1 and 0 <= stats.acceptance_rate <= 1

    def test_self_draft_accepts_everything(self, models):
        """Draft == target: every proposal matches, rounds collapse to
        ~max_new/(k+1) and acceptance is 100%."""
        target, cfg, _, _ = models
        prompt = [3, 4, 5]
        want = _solo(target, cfg, prompt, 12)
        stats = SpecStats()
        got = speculative_generate(target, cfg, target, cfg, prompt,
                                   max_new_tokens=12, k=3, stats=stats)
        assert got == want
        assert stats.acceptance_rate == 1.0
        assert stats.rounds <= -(-12 // 4) + 1   # ceil(12/(k+1)) slack 1

    def test_various_prompts_and_lengths(self, models):
        target, cfg, draft, dcfg = models
        for prompt, n in [([1], 7), ([9, 8, 7, 6, 5], 5), ([100] * 9, 10)]:
            want = _solo(target, cfg, prompt, n)
            got = speculative_generate(target, cfg, draft, dcfg, prompt,
                                       max_new_tokens=n, k=3)
            assert got == want, (prompt, n)

    def test_validation(self, models):
        target, cfg, draft, dcfg = models
        with pytest.raises(ValueError, match="empty"):
            speculative_generate(target, cfg, draft, dcfg, [], 4)
        with pytest.raises(ValueError, match="max_len"):
            speculative_generate(target, cfg, draft, dcfg, [1, 2], 8,
                                 k=2, max_len=4)


class TestMoeExactness:
    """MoE targets hold the same bit-exactness bar: verify windows route
    ``no_drop`` (every token as if decoded alone — the T=1 oracle), the
    prompt prefill mirrors the oracle's real-length capacity pressure."""

    @pytest.fixture(scope="class")
    def moe(self):
        from kubetorch_tpu.models.moe import MoeConfig, moe_init
        mcfg = MoeConfig.tiny(dtype=jnp.float32, remat=False,
                              attn_impl="xla")
        return moe_init(jax.random.PRNGKey(1), mcfg), mcfg

    @pytest.mark.parametrize("k", [2, 4])
    def test_moe_target_dense_draft(self, models, moe, k):
        _, _, draft, dcfg = models
        mo, mcfg = moe
        for prompt, n in [([5, 17, 42, 99], 10), ([7] * 9, 8)]:
            want = _solo(mo, mcfg, prompt, n)
            stats = SpecStats()
            got = speculative_generate(mo, mcfg, draft, dcfg, prompt,
                                       max_new_tokens=n, k=k, stats=stats)
            assert got == want, (prompt, n, k)
            assert stats.rounds >= 1

    def test_moe_self_draft_accepts_everything(self, moe):
        """MoE drafting for itself: proposals must equal the target's own
        greedy choices — any draft/verify routing mismatch shows up as a
        sub-1.0 acceptance rate before it even breaks exactness."""
        mo, mcfg = moe
        prompt = [3, 4, 5]
        want = _solo(mo, mcfg, prompt, 10)
        stats = SpecStats()
        got = speculative_generate(mo, mcfg, mo, mcfg, prompt,
                                   max_new_tokens=10, k=3, stats=stats)
        assert got == want
        assert stats.acceptance_rate == 1.0

    def test_moe_draft_dense_target(self, models, moe):
        target, cfg, _, _ = models
        mo, mcfg = moe
        prompt = [9, 8, 7]
        want = _solo(target, cfg, prompt, 8)
        got = speculative_generate(target, cfg, mo, mcfg, prompt,
                                   max_new_tokens=8, k=3)
        assert got == want
