"""Step-time burn-down (ISSUE 12): overlapped gradient reduction, the
async two-phase checkpoint snapshot, the remat/donation audit surface, and
the step-anatomy metrics knob.

The overlap claims are pinned on the conftest's forced 8-device host mesh:
bucketed per-microbatch reduce-scatter must be *bit-comparable* to the
plain accumulation path, and the fp32 accumulator must hold one fsdp shard
per device. The snapshot claims are pinned against a sleep-leaf transfer
fake: ``maybe_save`` must return in O(dispatch), never blocking a full
host copy.
"""

import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.level("minimal")


# ---------------------------------------------------------------------------
# Overlapped gradient reduction (tentpole 1)
# ---------------------------------------------------------------------------


def _tiny_setup():
    import jax
    import jax.numpy as jnp
    import optax

    from kubetorch_tpu.models.llama import LlamaConfig, llama_init, llama_loss

    cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)
    opt = optax.adam(1e-2)
    loss = lambda p, t, y: llama_loss(p, t, y, cfg)  # noqa: E731
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    # a FACTORY, not a tree: the donating step consumes (or buffer-aliases)
    # its input state, so every step invocation needs a fresh init
    make_params = lambda: llama_init(jax.random.PRNGKey(0), cfg)  # noqa: E731
    return cfg, opt, loss, make_params, batch


@pytest.mark.level("release")
def test_overlap_bit_comparable_to_plain_accum(cpu_mesh_devices):
    """Bucketed per-microbatch reduction must produce the SAME numbers as
    the end-of-scan bulk reduce — loss, grad_norm, accumulated grads, and
    the post-update params, on the 8-device forced-host mesh."""
    import jax

    from kubetorch_tpu.parallel.mesh import build_mesh
    from kubetorch_tpu.parallel.sharding import LLAMA_RULES
    from kubetorch_tpu.train import init_train_state, make_train_step

    cfg, opt, loss, make_params, batch = _tiny_setup()
    mesh = build_mesh({"data": 2, "fsdp": 4})
    states, metrics, grads = {}, {}, {}
    for overlap in (False, True):
        step = make_train_step(loss, optimizer=opt, mesh=mesh,
                               rules=LLAMA_RULES, accum_steps=4,
                               overlap_grads=overlap)
        state = step.shard_state(init_train_state(make_params(), opt))
        b = {k: jax.device_put(v, step.batch_sharding)
             for k, v in batch.items()}
        _, g = step.grads_fn(state.params, b)
        grads[overlap] = jax.device_get(g)
        state, m = step(state, b)
        states[overlap] = jax.device_get(state.params)
        metrics[overlap] = {k: float(v) for k, v in m.items()}

    assert metrics[False]["loss"] == metrics[True]["loss"]
    assert metrics[False]["grad_norm"] == metrics[True]["grad_norm"]
    for a, b2 in zip(jax.tree_util.tree_leaves(grads[False]),
                     jax.tree_util.tree_leaves(grads[True])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b2))
    for a, b2 in zip(jax.tree_util.tree_leaves(states[False]),
                     jax.tree_util.tree_leaves(states[True])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b2))


@pytest.mark.level("release")
def test_overlap_accumulator_is_one_fsdp_shard(cpu_mesh_devices):
    """With overlap on, every fsdp-sharded grad leaf's per-device bytes =
    leaf/8 (the fsdp shard), and the specs match the param rules — the
    accumulator constraint, observable on ``grads_fn``'s output."""
    import jax
    from jax.sharding import PartitionSpec as P

    from kubetorch_tpu.parallel.mesh import build_mesh
    from kubetorch_tpu.parallel.sharding import LLAMA_RULES
    from kubetorch_tpu.train import init_train_state, make_train_step

    cfg, opt, loss, make_params, batch = _tiny_setup()
    mesh = build_mesh({"fsdp": 8})
    step = make_train_step(loss, optimizer=opt, mesh=mesh,
                           rules=LLAMA_RULES, accum_steps=4,
                           overlap_grads=True)
    state = step.shard_state(init_train_state(make_params(), opt))
    b = {k: jax.device_put(v, step.batch_sharding)
         for k, v in batch.items()}
    _, g = step.grads_fn(state.params, b)
    assert g["layers"]["wq"].sharding.spec == P(None, "fsdp")
    assert g["embed"].sharding.spec == P(None, "fsdp")
    for leaf in (g["layers"]["wq"], g["layers"]["w_down"], g["embed"],
                 g["lm_head"]):
        assert leaf.addressable_shards[0].data.size * 8 == leaf.size


def test_overlap_requires_mesh():
    from kubetorch_tpu.train import make_train_step

    with pytest.raises(ValueError, match="overlap_grads"):
        make_train_step(lambda p, t, y: 0.0, overlap_grads=True)


# ---------------------------------------------------------------------------
# Metrics knob (satellite 1)
# ---------------------------------------------------------------------------


@pytest.mark.level("release")
def test_metrics_opt_in():
    """metrics=("loss",) drops the grad_norm full-tree reduction from the
    hot path; default keeps current behavior; unknown names refuse."""
    from kubetorch_tpu.train import init_train_state, make_train_step

    cfg, opt, loss, make_params, batch = _tiny_setup()
    with pytest.raises(ValueError, match="unknown step metrics"):
        make_train_step(loss, metrics=("loss", "learning_rate"))

    lean = make_train_step(loss, optimizer=opt, metrics=("loss",))
    _, m = lean(init_train_state(make_params(), opt), batch)
    assert "grad_norm" not in m and "loss" in m and "step" in m

    full = make_train_step(loss, optimizer=opt)
    _, m2 = full(init_train_state(make_params(), opt), batch)
    assert "grad_norm" in m2 and "loss" in m2


@pytest.mark.level("release")
def test_step_compute_phase_observed():
    """Every wrapper call lands one kt_train_step_seconds{phase=compute}
    observation — the series the perf gate's train_step stage reads."""
    from kubetorch_tpu import telemetry
    from kubetorch_tpu.train import init_train_state, make_train_step

    cfg, opt, loss, make_params, batch = _tiny_setup()
    hist = telemetry.train_metrics()["step_seconds"]
    before = hist.count(phase="compute")
    step = make_train_step(loss, optimizer=opt)
    state = init_train_state(make_params(), opt)
    state, _ = step(state, batch)
    state, _ = step(state, batch)
    assert hist.count(phase="compute") == before + 2


# ---------------------------------------------------------------------------
# Remat policy threading (tentpole 3)
# ---------------------------------------------------------------------------


def test_resolve_remat_policy_names():
    from kubetorch_tpu.models.common import resolve_remat_policy

    assert resolve_remat_policy(None) is None
    assert resolve_remat_policy("none") is None
    assert callable(resolve_remat_policy("dots"))
    assert callable(resolve_remat_policy("nothing_saveable"))
    custom = lambda *a, **k: True  # noqa: E731
    assert resolve_remat_policy(custom) is custom
    with pytest.raises(ValueError, match="unknown remat policy"):
        resolve_remat_policy("dotz")


@pytest.mark.level("release")
def test_remat_policy_same_numbers_less_memory_pressure():
    """Named policies change WHERE activations are saved, never the math:
    loss/grads identical across none/dots/nothing_saveable, both via the
    model config and via make_train_step's wrap."""
    import jax

    from kubetorch_tpu.models.llama import (LlamaConfig, llama_init,
                                            llama_loss)
    from kubetorch_tpu.train import init_train_state, make_train_step

    import optax

    losses, norms = [], []
    for policy in (None, "none", "dots", "nothing_saveable"):
        cfg = LlamaConfig.tiny(attn_impl="xla", dtype=jax.numpy.float32,
                               remat=False, remat_policy=policy)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        opt = optax.adam(1e-2)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        batch = {"tokens": tokens, "targets": jax.numpy.roll(tokens, -1, 1)}
        step = make_train_step(
            lambda p, t, y, c=cfg: llama_loss(p, t, y, c), optimizer=opt,
            remat_policy=policy)
        _, m = step(init_train_state(params, opt), batch)
        losses.append(float(m["loss"]))
        norms.append(float(m["grad_norm"]))
    assert len(set(losses)) == 1, losses
    assert max(norms) - min(norms) < 1e-5, norms


# ---------------------------------------------------------------------------
# _opt_shardings recursion (satellite 4)
# ---------------------------------------------------------------------------


@pytest.mark.level("release")
def test_opt_shardings_namedtuple_and_dict_recursion(cpu_mesh_devices):
    """The structural matcher must recurse through namedtuples, dicts, and
    lists, replicate scalar leaves, and hand the param shardings to every
    subtree that mirrors the param structure — never shape-matching."""
    import collections

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubetorch_tpu.parallel.mesh import build_mesh
    from kubetorch_tpu.train.train_step import _opt_shardings

    mesh = build_mesh({"fsdp": 8})
    params = {"a": jnp.zeros((8, 8)), "b": jnp.zeros((8,))}
    param_sh = {"a": NamedSharding(mesh, P("fsdp", None)),
                "b": NamedSharding(mesh, P())}
    Adam = collections.namedtuple("Adam", ["mu", "nu", "count"])
    opt_state = (Adam(mu={"a": jnp.zeros((8, 8)), "b": jnp.zeros((8,))},
                      nu={"a": jnp.zeros((8, 8)), "b": jnp.zeros((8,))},
                      count=jnp.zeros(())),
                 [{"a": jnp.ones((8, 8)), "b": jnp.ones((8,))},
                  jnp.zeros((3,))])
    sh = _opt_shardings(opt_state, params, param_sh, mesh)
    assert isinstance(sh[0], Adam)                       # namedtuple kept
    assert sh[0].mu == param_sh and sh[0].nu == param_sh  # structural match
    assert sh[0].count.spec == P()                       # scalar replicated
    assert isinstance(sh[1], list)
    assert sh[1][0] == param_sh                          # dict subtree match
    assert sh[1][1].spec == P()                          # stray leaf


# ---------------------------------------------------------------------------
# Async snapshot (tentpole 2)
# ---------------------------------------------------------------------------


class _SleepLeaf:
    """Transfer fake: materializing the value costs ``delay`` seconds (a
    modeled D2H copy); dispatching the async copy costs nothing."""

    def __init__(self, arr, delay=0.3):
        self.arr = arr
        self.delay = delay
        self.async_copies = 0

    def copy_to_host_async(self):
        self.async_copies += 1

    def __array__(self, dtype=None):
        time.sleep(self.delay)
        return self.arr if dtype is None else self.arr.astype(dtype)


def _store_app(root):
    from kubetorch_tpu.data_store.store_server import create_store_app
    return lambda: create_store_app(str(root))


def test_maybe_save_never_blocks_a_host_copy(tmp_path):
    """THE regression test: ``maybe_save`` must return in O(dispatch) —
    against a tree whose every leaf takes 0.3s to copy, the inline stall
    must be far below ONE leaf's copy, the async copies must have been
    fanned out inline, and the committed bytes must still be exact."""
    import jax  # noqa: F401  (activates the device-leaf snapshot path)

    from kubetorch_tpu.train import checkpoint as ck
    from tests.assets.threaded_server import ThreadedAiohttpServer

    leaves = {f"w{i}": _SleepLeaf(np.full(64, float(i), np.float32))
              for i in range(4)}
    with ThreadedAiohttpServer(_store_app(tmp_path / "store")) as srv:
        c = ck.Checkpointer("job/async-snap", store_url=srv.url, every=1)
        t0 = time.perf_counter()
        fut = c.maybe_save(leaves, 1)
        inline = time.perf_counter() - t0
        assert fut is not None
        assert inline < 0.15, \
            f"maybe_save blocked {inline:.3f}s >= one 0.3s host copy"
        assert all(leaf.async_copies == 1 for leaf in leaves.values()), \
            "D2H fan-out must be dispatched inline"
        assert c.flush(timeout=30) == 1
        restored, step = c.restore()
        assert step == 1
        assert (restored["w3"] == 3.0).all()


def test_maybe_save_inline_gather_escape_hatch(tmp_path, monkeypatch):
    """KT_CKPT_INLINE_GATHER=1 restores the fully-blocking snapshot for
    donated training loops (docs/operations.md)."""
    import jax  # noqa: F401

    from kubetorch_tpu.train import checkpoint as ck
    from tests.assets.threaded_server import ThreadedAiohttpServer

    monkeypatch.setenv("KT_CKPT_INLINE_GATHER", "1")
    leaves = {"w": _SleepLeaf(np.ones(8, np.float32), delay=0.2)}
    with ThreadedAiohttpServer(_store_app(tmp_path / "store")) as srv:
        c = ck.Checkpointer("job/inline-snap", store_url=srv.url, every=1)
        t0 = time.perf_counter()
        fut = c.maybe_save(leaves, 1)
        inline = time.perf_counter() - t0
        assert inline >= 0.2, "inline-gather mode must block the host copy"
        fut.result(timeout=30)


def test_snapshot_donation_race_is_typed():
    """A leaf donated before the IO thread gathers must fail with the
    explanatory error, not a bare 'Array has been deleted'."""
    import jax
    import jax.numpy as jnp

    from kubetorch_tpu.train.checkpoint import _snapshot_async

    x = jnp.arange(1024.0)
    gather = _snapshot_async({"w": x})
    x.delete()                       # what a donating step call does
    with pytest.raises(RuntimeError, match="raced buffer donation"):
        gather()


def test_snapshot_pure_numpy_passthrough():
    """A host tree never copies — same objects, zero gather cost (the
    elastic tests' numpy states keep their pre-ISSUE-12 semantics)."""
    from kubetorch_tpu.train.checkpoint import _host_tree, _snapshot_async

    tree = {"a": np.arange(4), "b": {"c": np.ones(2)}}
    gathered = _snapshot_async(tree)()
    assert gathered["a"] is tree["a"] and gathered["b"]["c"] is tree["b"]["c"]
    assert _host_tree(tree)["a"] is tree["a"]


# ---------------------------------------------------------------------------
# HBM audit (tentpole 3)
# ---------------------------------------------------------------------------


@pytest.mark.level("release")
def test_hbm_audit_reports_and_flags_donation(cpu_mesh_devices):
    from kubetorch_tpu.train.hbm_audit import audit_llama, format_audit

    r = audit_llama("tiny", batch=8, seq=64, mesh_axes={"fsdp": 8},
                    accum_steps=2, remat_policy="dots")
    b = r["per_device_bytes"]
    assert b["params"] > 0 and b["opt_state"] > b["params"]  # adam 2x fp32
    assert b["activations_temp"] > 0
    assert r["donation"]["enabled"]
    # the overwhelming majority of state leaves must alias in place
    assert r["donation"]["donated_leaves"] >= r["donation"]["state_leaves"] - 5
    assert "hbm audit" in format_audit(r)

    r_off = audit_llama("tiny", batch=8, seq=64, donate=False)
    assert r_off["donation"]["donated_leaves"] == 0
    assert len(r_off["donation"]["undonated_paths"]) == \
        r_off["donation"]["state_leaves"]
    assert "double-buffered" in r_off["hint"]


def test_hbm_audit_alias_parse():
    from kubetorch_tpu.train.hbm_audit import parse_donated_params

    head = ('HloModule jit_step, is_scheduled=true, input_output_alias='
            '{ {0}: (0, {}, may-alias), {1}: (3, {}, may-alias), '
            '{2,1}: (17, {}, must-alias) }, entry_computation_layout='
            '{(f32[8]{0})->f32[8]{0}}')
    assert parse_donated_params(head) == {0, 3, 17}
    assert parse_donated_params("HloModule bare") == set()
