"""Crash-consistent, self-healing data store (ISSUE 4).

Deterministic proofs of every recovery path: kill the store mid-PUT
(``torn-write``), rot stored bytes (``corrupt-blob`` / direct flips), fill
the disk (``disk-full``) — then assert the durable-write layer, startup
recovery, scrubber quarantine, and client-side hash verification leave no
wrong answer visible anywhere. ``make test-store-chaos`` runs this file.
"""

import hashlib
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest
import requests

pytestmark = [pytest.mark.level("minimal"), pytest.mark.chaos]

from kubetorch_tpu.data_store import durability, scrub
from kubetorch_tpu.exceptions import DataCorruptionError, StoreFullError
from kubetorch_tpu.utils.procs import free_port, kill_process_tree, wait_for_port
from tests.assets.threaded_server import ThreadedAiohttpServer


def _store_app(root):
    from kubetorch_tpu.data_store.store_server import create_store_app
    return lambda: create_store_app(str(root))


def _b2(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=20).hexdigest()


def _spawn_store(root, port, extra_env=None):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubetorch_tpu.data_store.store_server",
         "--host", "127.0.0.1", "--port", str(port), "--root", str(root)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    assert wait_for_port("127.0.0.1", port, timeout=30)
    return proc


# ---------------------------------------------------------------------------
# Acceptance: kill-at-any-point safety (torn-write → restart → clean)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_torn_write_sigkill_then_restart_recovers(tmp_path):
    """SIGKILL the store mid-PUT (torn-write chaos), restart on the same
    --root: zero .tmp orphans, no partial value visible to GET or /kv/diff,
    and a clean re-upload succeeds."""
    root = tmp_path / "store"
    port = free_port()
    proc = _spawn_store(root, port,
                        {"KT_CHAOS": "torn-write:1024@/kv/ckpt",
                         "KT_CHAOS_SEED": "1234"})
    url = f"http://127.0.0.1:{port}"
    body = bytes(range(256)) * 64                  # 16 KiB > torn_bytes
    meta = json.dumps({"blake2b": _b2(body)})
    try:
        with pytest.raises(requests.RequestException):
            requests.put(f"{url}/kv/ckpt/w", data=body,
                         headers={"X-KT-Meta": meta}, timeout=30)
    finally:
        proc.wait(timeout=30)                      # chaos SIGKILLed it
    # the kill left a staged partial on disk — the exact orphan recovery
    # must sweep
    orphans = list(root.rglob("*.tmp"))
    assert orphans, "torn-write chaos should have staged a partial .tmp"

    port2 = free_port()
    proc2 = _spawn_store(root, port2)              # same root, no chaos
    url2 = f"http://127.0.0.1:{port2}"
    try:
        assert not list(root.rglob("*.tmp")), "recovery must sweep orphans"
        assert requests.get(f"{url2}/kv/ckpt/w", timeout=30).status_code == 404
        r = requests.post(f"{url2}/kv/diff",
                          json={"keys": {"ckpt/w": _b2(body)}}, timeout=30)
        assert r.json()["missing"] == ["ckpt/w"]
        # clean re-upload round-trips
        r = requests.put(f"{url2}/kv/ckpt/w", data=body,
                         headers={"X-KT-Meta": meta}, timeout=30)
        assert r.status_code == 200
        assert requests.get(f"{url2}/kv/ckpt/w", timeout=30).content == body
        r = requests.post(f"{url2}/kv/diff",
                          json={"keys": {"ckpt/w": _b2(body)}}, timeout=30)
        assert r.json()["missing"] == []
    finally:
        kill_process_tree(proc2.pid)


def test_startup_recovery_quarantines_torn_final_files(tmp_path):
    """An unclean death can also tear a file already renamed to its final
    name (rename persisted, data pages lost). With no clean-shutdown
    marker, startup re-verifies everything and quarantines the liars."""
    from kubetorch_tpu.data_store.store_server import StoreState

    root = tmp_path / "store"
    good = b"good blob bytes"
    gh = _b2(good)
    (root / "blobs" / gh[:2]).mkdir(parents=True)
    (root / "blobs" / gh[:2] / gh).write_bytes(good)
    bh = _b2(b"the full original content")
    (root / "blobs" / bh[:2]).mkdir(parents=True)
    (root / "blobs" / bh[:2] / bh).write_bytes(b"the full or")   # truncated
    (root / "kv").mkdir(parents=True)
    (root / "kv" / "k1").write_bytes(b"torn")
    (root / "kv" / "k1.meta").write_text(
        json.dumps({"blake2b": _b2(b"complete value"), "size": 14}))
    (root / "kv" / "k1.abc123.tmp").write_bytes(b"orphan")
    (root / "trees").mkdir(parents=True)

    st = StoreState(str(root))
    rep = st.recovery
    assert not rep["clean_shutdown"]
    assert rep["tmp_swept"] == 1
    assert rep["quarantined"] == 2                 # bad blob + kv pair
    assert (root / "blobs" / gh[:2] / gh).is_file()       # good one kept
    assert not (root / "blobs" / bh[:2] / bh).exists()
    assert not (root / "kv" / "k1").exists()
    assert not (root / "kv" / "k1.meta").exists(), \
        "stale meta must go with the data or /kv/diff lies forever"
    qdir = root / scrub.QUARANTINE_DIR
    assert sum(1 for p in qdir.iterdir()
               if not p.name.endswith(".why")) == 3  # blob + kv data + meta


def test_clean_shutdown_marker_bounds_verification(tmp_path):
    """A graceful stop stamps the marker; the next startup skips re-hashing
    objects older than it (the normal fast path)."""
    from kubetorch_tpu.data_store.store_server import StoreState

    root = tmp_path / "store"
    st = StoreState(str(root))
    blob = b"x" * 128
    h = _b2(blob)
    p = root / "blobs" / h[:2] / h
    p.parent.mkdir(parents=True)
    p.write_bytes(blob)
    old = os.stat(p).st_mtime - 120
    os.utime(p, (old, old))
    st.mark_clean_shutdown()

    st2 = StoreState(str(root))
    assert st2.recovery["clean_shutdown"]
    assert st2.recovery["verified"] == 0           # marker bounded the sweep
    # marker is consumed: a crash from here on is detectable again
    st3 = StoreState(str(root))
    assert not st3.recovery["clean_shutdown"]
    assert st3.recovery["verified"] == 1


# ---------------------------------------------------------------------------
# Acceptance: corrupt-blob → scrubber quarantine → client repair
# ---------------------------------------------------------------------------


def test_corrupt_blob_chaos_scrub_quarantine_reupload(tmp_path, monkeypatch):
    """corrupt-blob chaos rots the stored blob; the scrubber quarantines it
    within one sweep; GET turns 404 (repair signal); re-upload heals."""
    blob = bytes(range(256)) * 8
    h = _b2(blob)
    monkeypatch.setenv("KT_CHAOS", f"corrupt-blob@/blob/{h}")
    monkeypatch.setenv("KT_CHAOS_SEED", "1234")
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")   # /scrub/run drives it
    root = tmp_path / "store"
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        assert requests.put(f"{srv.url}/blob/{h}", data=blob,
                            timeout=30).status_code == 200
        # the chaos-consumed GET serves rotten bytes AND persists the rot
        rotten = requests.get(f"{srv.url}/blob/{h}", timeout=30)
        assert rotten.status_code == 200 and rotten.content != blob

        rep = requests.post(f"{srv.url}/scrub/run", timeout=60).json()
        assert rep["quarantined"] == 1
        status = requests.get(f"{srv.url}/scrub/status", timeout=30).json()
        assert status["sweeps"] == 1 and status["quarantine_files"] == 1
        assert requests.get(f"{srv.url}/blob/{h}",
                            timeout=30).status_code == 404

        assert requests.put(f"{srv.url}/blob/{h}", data=blob,
                            timeout=30).status_code == 200
        assert requests.get(f"{srv.url}/blob/{h}", timeout=30).content == blob
        rep = requests.post(f"{srv.url}/scrub/run", timeout=60).json()
        assert rep["quarantined"] == 0             # healed store scrubs clean


def test_client_get_raises_typed_corruption_then_repair(tmp_path, monkeypatch):
    """End-to-end kv corruption: flip a byte under a pytree leaf → kt.get
    raises DataCorruptionError; scrub + re-put repairs; get succeeds."""
    import numpy as np
    from kubetorch_tpu.data_store import commands as ds

    monkeypatch.delenv("POD_IP", raising=False)
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    root = tmp_path / "store"
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        tree = {"w": np.arange(64, dtype=np.float32)}
        ds.put("rot/ckpt", tree, store_url=srv.url)

        leaf = root / "kv" / durability.escape_key("rot/ckpt/w")
        raw = bytearray(leaf.read_bytes())
        raw[0] ^= 0xFF
        leaf.write_bytes(bytes(raw))

        with pytest.raises(DataCorruptionError) as ei:
            ds.get("rot/ckpt", store_url=srv.url)
        assert ei.value.source == "store" and ei.value.key == "rot/ckpt/w"

        rep = requests.post(f"{srv.url}/scrub/run", timeout=60).json()
        assert rep["quarantined"] == 1
        # quarantined leaf counts as missing → the re-put re-uploads it
        again = ds.put("rot/ckpt", tree, store_url=srv.url)
        assert again["skipped"] == 0
        out = ds.get("rot/ckpt", store_url=srv.url)
        np.testing.assert_array_equal(out["w"], tree["w"])


def test_pull_tree_detects_corrupt_blob(tmp_path, monkeypatch):
    """ktsync pull verifies each streamed blob against its manifest hash —
    corrupt store bytes raise typed instead of landing in the dest tree."""
    from kubetorch_tpu.data_store.sync import push_tree, pull_tree

    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    root = tmp_path / "store"
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "model.py").write_text("weights = 42\n")
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        push_tree(srv.url, "code/app", str(proj))
        blob = next(p for p in (root / "blobs").rglob("*") if p.is_file())
        raw = bytearray(blob.read_bytes())
        raw[0] ^= 0xFF
        blob.write_bytes(bytes(raw))

        dest = tmp_path / "dest"
        with pytest.raises(DataCorruptionError):
            pull_tree(srv.url, "code/app", str(dest))
        assert not (dest / "model.py").exists()
        assert not list(dest.glob("*.ktsync-tmp"))

        # repair: re-push (the diff sees the blob present — scrub first)
        requests.post(f"{srv.url}/scrub/run", timeout=60)
        push_tree(srv.url, "code/app", str(proj))
        pull_tree(srv.url, "code/app", str(dest))
        assert (dest / "model.py").read_text() == "weights = 42\n"


def test_corrupt_peer_evicted_and_origin_repairs(tmp_path, monkeypatch):
    """A peer serving corrupt bytes is treated like a dead one: typed
    detection → /route/failed eviction → transparent re-fetch from the
    origin store — the get still SUCCEEDS."""
    import numpy as np
    from kubetorch_tpu.data_store import commands as ds

    monkeypatch.setenv("POD_IP", "127.0.0.1")
    monkeypatch.setenv("KT_SERVER_PORT", str(free_port()))
    monkeypatch.setenv("KT_DATA_CACHE_DIR", str(tmp_path / "cache"))
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    root = tmp_path / "store"
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        arr = np.arange(32, dtype=np.float32)
        ds.put("p2p/rot", {"w": arr}, store_url=srv.url)

        failed_reports = []
        fetcher = ds._RoutedFetcher(srv.url, "p2p/rot", peer=True)
        fetcher.peer_url = "http://10.9.9.9:1"
        fetcher._resolved = True
        good = np.asarray(arr).tobytes()
        corrupt = b"\x7f" + good[1:]               # differs from good[0]
        meta = {"dtype": "float32", "shape": [32], "kind": "array",
                "blake2b": _b2(good)}
        monkeypatch.setattr(
            fetcher, "_fetch_from_peer",
            lambda subkey, timeout: ds._CachedResponse(corrupt, meta))
        monkeypatch.setattr(fetcher, "_report_failed",
                            lambda peer: failed_reports.append(peer))

        r = fetcher.fetch("p2p/rot/w", expect_hash=_b2(good))
        assert r.status_code == 200 and r.content == good   # origin repaired
        assert failed_reports == ["http://10.9.9.9:1"]      # peer evicted
        assert fetcher.peer_url is None


def test_corrupt_pod_cache_self_evicts(tmp_path, monkeypatch):
    """A rotten pod-cache entry is evicted on read (never served to this
    pod or its children); the get falls through to the store."""
    import numpy as np
    from kubetorch_tpu.data_store import commands as ds
    from kubetorch_tpu.data_store import peer_cache

    monkeypatch.setenv("POD_IP", "127.0.0.1")
    monkeypatch.setenv("KT_SERVER_PORT", str(free_port()))
    monkeypatch.setenv("KT_DATA_CACHE_DIR", str(tmp_path / "cache"))
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    root = tmp_path / "store"
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        arr = np.full((16,), 3, dtype=np.int32)
        ds.put("cache/rot", {"w": arr}, store_url=srv.url)
        good = np.asarray(arr).tobytes()
        peer_cache.cache_put(
            "cache/rot/w", b"\xff" + good[1:],
            {"dtype": "int32", "shape": [16], "kind": "array",
             "blake2b": _b2(good)})
        assert peer_cache.cache_get("cache/rot/w") is None   # self-evicted
        out = ds.get("cache/rot", store_url=srv.url, peer=True)
        np.testing.assert_array_equal(out["w"], arr)


# ---------------------------------------------------------------------------
# disk-full → typed, non-retryable StoreFullError
# ---------------------------------------------------------------------------


def test_disk_full_maps_to_typed_store_full_error(tmp_path, monkeypatch):
    """A 507 is a capacity verdict: ONE injected disk-full fails the put
    with typed StoreFullError — were it retried, the second attempt would
    pass chaos and succeed, masking the full disk."""
    import numpy as np
    from kubetorch_tpu.data_store import commands as ds

    monkeypatch.delenv("POD_IP", raising=False)
    monkeypatch.setenv("KT_CHAOS", "disk-full@/kv/full")
    monkeypatch.setenv("KT_CHAOS_SEED", "1234")
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    root = tmp_path / "store"
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        with pytest.raises(StoreFullError) as ei:
            ds.put("full/ckpt", {"w": np.ones(8, np.float32)},
                   store_url=srv.url)
        assert ei.value.status_code == 507
        assert srv.app["chaos"].injected == 1
        # chaos schedule exhausted → the retry-after-free-space story works
        stats = ds.put("full/ckpt", {"w": np.ones(8, np.float32)},
                       store_url=srv.url)
        assert stats["leaves"] == 1


def test_enospc_classifier():
    import errno

    assert durability.is_disk_full(OSError(errno.ENOSPC, "no space"))
    assert durability.is_disk_full(OSError(errno.EDQUOT, "quota"))
    assert not durability.is_disk_full(OSError(errno.EACCES, "denied"))
    assert not durability.is_disk_full(ValueError("x"))


# ---------------------------------------------------------------------------
# Scrubber unit behavior
# ---------------------------------------------------------------------------


def test_scrubber_double_checks_kv_race(tmp_path):
    """A kv pair replaced between meta read and data hash must NOT be
    quarantined — the double-check re-reads before condemning."""
    root = tmp_path / "store"
    (root / "kv").mkdir(parents=True)
    val = b"consistent value"
    (root / "kv" / "k").write_bytes(val)
    (root / "kv" / "k.meta").write_text(
        json.dumps({"blake2b": _b2(val), "size": len(val)}))
    assert not scrub._verify_kv_pair(root, root / "kv" / "k",
                                     root / "kv" / "k.meta")
    assert (root / "kv" / "k").is_file()


def test_gc_reclaims_unreferenced_blobs(tmp_path, monkeypatch):
    """tree_delete strands its blobs; /gc with grace 0 reclaims exactly the
    unreferenced ones and keeps everything a manifest still points at."""
    from kubetorch_tpu.data_store.sync import push_tree

    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    root = tmp_path / "store"
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "a.py").write_text("a = 1\n")
    (proj / "b.py").write_text("b = 2\n")
    with ThreadedAiohttpServer(_store_app(root)) as srv:
        push_tree(srv.url, "code/app", str(proj))
        stray = b"never referenced by any manifest"
        sh = _b2(stray)
        assert requests.put(f"{srv.url}/blob/{sh}", data=stray,
                            timeout=30).status_code == 200

        rep = requests.post(f"{srv.url}/gc", json={"grace_s": 0},
                            timeout=60).json()
        assert rep["deleted"] == 1 and rep["bytes_freed"] == len(stray)
        assert rep["kept"] == 2                     # manifest-pinned blobs
        # young blobs survive the default grace window (in-flight uploads)
        assert requests.put(f"{srv.url}/blob/{sh}", data=stray,
                            timeout=30).status_code == 200
        rep = requests.post(f"{srv.url}/gc", timeout=60).json()
        assert rep["deleted"] == 0

        requests.delete(f"{srv.url}/tree/code/app", timeout=30)
        rep = requests.post(f"{srv.url}/gc", json={"grace_s": 0},
                            timeout=60).json()
        assert rep["deleted"] == 3                  # everything reclaimed


def test_durable_replace_fsyncs_data_and_dir(tmp_path, monkeypatch):
    """KT_STORE_FSYNC=1 (default) pairs the commit rename with data + parent
    -dir fsync; =0 skips both (throwaway roots)."""
    calls = []
    real = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: calls.append(fd) or real(fd))

    monkeypatch.setenv("KT_STORE_FSYNC", "1")
    durability.durable_write_bytes(tmp_path / "f1", b"payload")
    assert len(calls) == 2                          # file + parent dir
    assert (tmp_path / "f1").read_bytes() == b"payload"

    calls.clear()
    monkeypatch.setenv("KT_STORE_FSYNC", "0")
    durability.durable_write_bytes(tmp_path / "f2", b"payload")
    assert calls == []
    assert (tmp_path / "f2").read_bytes() == b"payload"
