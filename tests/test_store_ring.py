"""Replicated, ring-sharded data store (ISSUE 7).

Placement determinism, R-way replica forwarding at write-quorum, proxy
reads, ring-epoch safety under membership change, TTL-driven
re-replication — and the chaos acceptance: SIGKILL a store node mid
multi-leaf put and mid pull_tree with ZERO client-visible failures.
``make test-ring`` runs this file.
"""

import hashlib
import json
import os
import time
from urllib.parse import quote, unquote

import numpy as np
import pytest
import requests

pytestmark = [pytest.mark.level("minimal"), pytest.mark.chaos]

from kubetorch_tpu.data_store import commands as ds
from kubetorch_tpu.data_store import netpool, ring
from kubetorch_tpu.data_store.store_server import RingState
from kubetorch_tpu.exceptions import (RingEpochMismatch, package_exception,
                                      rehydrate_exception)
from kubetorch_tpu.train import checkpoint as ck
from tests.assets.store_fleet import (SubprocessStoreFleet,
                                      ThreadedStoreFleet)
from tests.assets.threaded_server import ThreadedAiohttpServer


@pytest.fixture(autouse=True)
def _ring_isolation(monkeypatch):
    """Every test starts with a fresh router cache, no fleet env leakage,
    and the peer fan-out off (POD_IP drives it; these tests cover the
    store ring, not P2P)."""
    monkeypatch.delenv("POD_IP", raising=False)
    monkeypatch.delenv("KT_STORE_NODES", raising=False)
    monkeypatch.setenv("KT_SCRUB_INTERVAL_S", "0")
    monkeypatch.setenv("KT_STORE_FSYNC", "0")
    ring.reset_rings()
    netpool.reset_breakers()
    yield
    ring.reset_rings()
    netpool.reset_breakers()


def _use_fleet(monkeypatch, fleet) -> None:
    for k, v in fleet.client_env().items():
        monkeypatch.setenv(k, v)
    ring.reset_rings()


def _kv_copies(fleet, key: str):
    """Which LIVE nodes hold ``key`` locally (strictly-local HEADs)."""
    holders = []
    for i, url in enumerate(fleet.urls):
        if getattr(fleet, "servers", None) is not None \
                and fleet.servers[i] is None:
            continue
        try:
            r = requests.head(f"{url}/kv/{quote(key, safe='/')}",
                              headers={ring.REPLICATED_HEADER: "1"},
                              timeout=10)
        except requests.RequestException:
            continue
        if r.status_code == 200:
            holders.append(url)
    return holders


def _tree(leaves=8, n=64, seed=0):
    rng = np.random.default_rng(seed)
    return {"layers": {f"w{i:02d}": rng.standard_normal(n).astype(np.float32)
                       for i in range(leaves)}}


# ---------------------------------------------------------------------------
# Placement units: deterministic, order-independent, quote/escape-stable
# ---------------------------------------------------------------------------


def test_ring_placement_order_independent():
    nodes = [f"http://10.0.0.{i}:8873" for i in range(5)]
    a = ring.HashRing(nodes)
    b = ring.HashRing(list(reversed(nodes)))
    c = ring.HashRing(nodes[2:] + nodes[:2])
    for key in ("ckpt/slot-0/layers/wq", "weights/step-0001/w", "x"):
        assert a.walk(key) == b.walk(key) == c.walk(key)
        assert a.replicas(key, 2) == a.walk(key)[:2]
        assert len(set(a.replicas(key, 3))) == 3


def test_ring_placement_spreads_keys():
    nodes = [f"http://10.0.0.{i}:8873" for i in range(3)]
    r = ring.HashRing(nodes)
    primaries = {r.walk(f"bench/leaf/{i}")[0] for i in range(64)}
    assert primaries == set(nodes), "64 keys must hit every primary"


def test_urlkey_quoted_keys_hash_identically():
    """The cross-node hash-stability contract: the wire form
    (``netpool.urlkey``) and disk form (``escape_key``) of a key must
    place EXACTLY like the raw key on every node, or two nodes would
    route one key to two replica sets."""
    from kubetorch_tpu.data_store import durability

    nodes = [f"http://10.0.0.{i}:8873" for i in range(4)]
    r = ring.HashRing(nodes)
    for key in ("plain/key", "sp ace/key", "pc%2Fnt/key", "uni/cöde",
                "tra%25il/%", "a/b/c.__kt_index__"):
        wire = unquote(netpool.urlkey(key))
        disk = durability.unescape_key(durability.escape_key(key))
        assert wire == disk == key
        assert r.walk(wire) == r.walk(key) == r.walk(disk)


def test_client_and_server_placement_agree():
    nodes = [f"http://10.1.0.{i}:8873" for i in range(3)]
    client = ring.StoreRing(nodes[0], nodes=nodes, epoch=1)
    server = RingState(nodes[1], nodes, epoch=1, replication=2, quorum=2)
    for key in ("ckpt/a", "ckpt/b/leaf", "tree/blob0123"):
        assert client.nodes_for(key)[:2] == server.walk(key)[:2]
        assert server.live_replicas(key) == server.walk(key)[:2]


def test_ring_epoch_mismatch_rehydrates_typed():
    exc = RingEpochMismatch("stale", expected=4, actual=2)
    back = rehydrate_exception(json.loads(json.dumps(package_exception(exc))))
    assert isinstance(back, RingEpochMismatch)
    assert back.expected == 4 and back.actual == 2


def test_single_origin_ring_sends_no_epoch_header(tmp_path):
    """KT_STORE_NODES unset → the degenerate ring: no discovery request,
    no epoch header — wire behavior identical to the pre-ring client."""
    from kubetorch_tpu.data_store.store_server import create_store_app

    with ThreadedAiohttpServer(
            lambda: create_store_app(str(tmp_path / "s"))) as srv:
        rg = ring.ring_for(srv.url)
        assert rg.size == 1 and rg.epoch is None
        stats = ds.put("solo/t", {"w": np.ones(4, np.float32)},
                       store_url=srv.url)
        assert stats["leaves"] == 1
        out = ds.get("solo/t", store_url=srv.url)
        np.testing.assert_array_equal(out["w"], np.ones(4, np.float32))


# ---------------------------------------------------------------------------
# Replication + failover (in-process fleet)
# ---------------------------------------------------------------------------


def test_put_replicates_every_key_to_quorum(tmp_path, monkeypatch):
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        tree = _tree(leaves=6)
        stats = ds.put("repl/ckpt", tree, store_url=fleet.urls[0])
        assert stats["leaves"] == 6 and stats["skipped"] == 0
        for i in range(6):
            key = f"repl/ckpt/layers/w{i:02d}"
            assert len(_kv_copies(fleet, key)) >= 2, \
                f"{key} must exist on >=2 nodes (W=2)"
        assert len(_kv_copies(fleet, "repl/ckpt.__kt_index__")) >= 2
        # any seed node serves the whole tree
        for url in fleet.urls:
            out = ds.get("repl/ckpt", store_url=url)
            np.testing.assert_array_equal(out["layers"]["w03"],
                                          tree["layers"]["w03"])


def test_node_loss_fails_over_and_delta_still_skips(tmp_path, monkeypatch):
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        tree = _tree(leaves=6, seed=1)
        ds.put("loss/ckpt", tree, store_url=fleet.urls[0])
        fleet.stop_node(1)
        out = ds.get("loss/ckpt", store_url=fleet.urls[1])  # dead seed, even
        np.testing.assert_array_equal(out["layers"]["w00"],
                                      tree["layers"]["w00"])
        # an identical re-put against the degraded ring still moves ~0
        # bytes: /kv/diff answers ring-wide from surviving replicas
        stats = ds.put("loss/ckpt", tree, store_url=fleet.urls[0])
        assert stats["skipped"] == 6
        # deterministic failover proof: pick a key whose PRIMARY is the
        # dead node (placement is deterministic, so search for one) and
        # clear the router's down-marking so it really tries it first
        rg = ring.ring_for(fleet.urls[0])
        dead = fleet.urls[1]
        probe = next(f"loss/probe/{i}" for i in range(256)
                     if ring.HashRing(rg.nodes).walk(
                         f"loss/probe/{i}")[0] == dead)
        rg.record_success(dead)
        before = ring._FAILOVERS.value(kind="connect")
        assert ds.get_json(probe, store_url=fleet.urls[0]) is None
        assert ring._FAILOVERS.value(kind="connect") > before


def test_any_node_proxies_keys_it_does_not_hold(tmp_path, monkeypatch):
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        val = np.arange(32, dtype=np.float32)
        ds.put("proxy/one", {"w": val}, store_url=fleet.urls[0])
        key = "proxy/one/w"
        holders = _kv_copies(fleet, key)
        others = [u for u in fleet.urls if u not in holders]
        assert others, "R=2 of 3 nodes must leave a non-holder"
        # a DIRECT client GET (no ring header) against the non-holder
        r = requests.get(f"{others[0]}/kv/{quote(key, safe='/')}",
                         timeout=30)
        assert r.status_code == 200
        assert r.content == val.tobytes()
        prom = requests.get(f"{others[0]}/metrics", timeout=10).text
        assert "kt_store_proxy_fetches_total" in prom


def test_tripped_breaker_on_one_replica_does_not_gate_siblings(
        tmp_path, monkeypatch):
    """Satellite: per-netloc breakers + ring failover. A dead replica
    trips ITS breaker; requests keep flowing to the sibling, and the
    open breaker is just another failover signal."""
    with ThreadedStoreFleet(tmp_path, n=2) as fleet:
        _use_fleet(monkeypatch, fleet)
        monkeypatch.setenv("KT_STORE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("KT_STORE_RETRIES", "1")
        val = np.ones(16, np.float32)
        rg = ring.ring_for(fleet.urls[0])
        # placement depends on the fleet's EPHEMERAL ports: pick a base key
        # whose leaf provably places node0 FIRST, so killing node0 puts a
        # refused connection (→ tripped breaker) on the request path every
        # run instead of only when the port hash happens to land that way
        base = next(f"brk/ckpt{i}" for i in range(64)
                    if rg.nodes_for(f"brk/ckpt{i}/w")[0] == fleet.urls[0])
        ds.put(base, {"w": val}, store_url=fleet.urls[0])
        fleet.stop_node(0)
        before = ring._FAILOVERS.value(kind="breaker")
        # repeated ops: first trips node0's breaker (refused), later ones
        # hit the open breaker and must STILL succeed via node1. Clearing
        # the router's own down-marking between ops forces each retry back
        # onto node0 first, so the OPEN BREAKER (not the liveness
        # ordering) is what the failover absorbs.
        for _ in range(3):
            rg.record_success(fleet.urls[0])
            out = ds.get(base, store_url=fleet.urls[0])
            np.testing.assert_array_equal(out["w"], val)
        from urllib.parse import urlsplit
        dead = urlsplit(fleet.urls[0]).netloc
        live = urlsplit(fleet.urls[1]).netloc
        assert netpool._BREAKERS[dead].state == "open"
        assert netpool._BREAKERS.get(live) is None or \
            netpool._BREAKERS[live].state == "closed"
        assert ring._FAILOVERS.value(kind="breaker") > before


# ---------------------------------------------------------------------------
# Membership change: epoch safety under concurrent writes (satellite)
# ---------------------------------------------------------------------------


def test_stale_epoch_rejected_typed_before_touching_disk(
        tmp_path, monkeypatch):
    with ThreadedStoreFleet(tmp_path, n=2, epoch=5) as fleet:
        _use_fleet(monkeypatch, fleet)
        r = requests.put(f"{fleet.urls[0]}/kv/stale/k", data=b"x",
                         headers={ring.RING_EPOCH_HEADER: "3"}, timeout=30)
        assert r.status_code == 409
        body = r.json()
        assert body["error_type"] == "RingEpochMismatch"
        exc = rehydrate_exception(body)
        assert exc.expected == 5 and exc.actual == 3
        # nothing landed
        assert requests.get(f"{fleet.urls[0]}/kv/stale/k",
                            timeout=10).status_code == 404


def test_membership_change_mid_put_lands_at_quorum_never_partial(
        tmp_path, monkeypatch):
    """THE satellite scenario: a node joins (epoch bump) while a
    multi-leaf put is in flight. In-flight leaves hit 409 +
    RingEpochMismatch, the router refreshes and re-routes transparently
    (the RetryPolicy-shaped absorption), and the put lands at quorum on
    the NEW ring — never a silent partial tree."""
    from kubetorch_tpu.data_store.store_server import create_store_app

    with ThreadedStoreFleet(tmp_path, n=3, epoch=1) as fleet:
        _use_fleet(monkeypatch, fleet)
        monkeypatch.setenv("KT_STORE_CONCURRENCY", "1")  # deterministic order
        joiner_port = __import__(
            "kubetorch_tpu.utils.procs", fromlist=["free_port"]).free_port()
        joiner_url = f"http://127.0.0.1:{joiner_port}"
        new_nodes = fleet.urls + [joiner_url]
        joiner_ring = RingState(joiner_url, new_nodes, epoch=2,
                                replication=2, quorum=2,
                                ttl_s=fleet.node_ttl_s)
        joiner = ThreadedAiohttpServer(
            lambda: create_store_app(str(tmp_path / "joiner"),
                                     ring=joiner_ring),
            port=joiner_port)
        joiner.__enter__()
        try:
            rg = ring.ring_for(fleet.urls[0])
            assert rg.epoch == 1
            state = {"puts": 0}
            orig = ds._kv_put

            def join_mid_put(url, key, data, meta, sess=None):
                state["puts"] += 1
                if state["puts"] == 3:
                    # the membership change lands between leaf uploads
                    fleet.post_ring(new_nodes, epoch=2)
                return orig(url, key, data, meta, sess)

            monkeypatch.setattr(ds, "_kv_put", join_mid_put)
            before = ring._FAILOVERS.value(kind="epoch")
            tree = _tree(leaves=8, seed=3)
            stats = ds.put("join/ckpt", tree, store_url=fleet.urls[0])
            monkeypatch.setattr(ds, "_kv_put", orig)
            assert stats["leaves"] == 8
            # the router noticed, refreshed, and re-routed at least once
            assert ring._FAILOVERS.value(kind="epoch") > before
            assert rg.epoch == 2 and joiner_url in rg.nodes
            # never a partial tree: every leaf readable and bit-exact,
            # from the old members AND the joiner
            for url in (fleet.urls[0], joiner_url):
                out = ds.get("join/ckpt", store_url=url)
                for name, arr in tree["layers"].items():
                    np.testing.assert_array_equal(out["layers"][name], arr)
        finally:
            joiner.__exit__()


# ---------------------------------------------------------------------------
# TTL re-replication + deletes + trees
# ---------------------------------------------------------------------------


def test_dead_node_past_ttl_rereplicated_by_scrub(tmp_path, monkeypatch):
    with ThreadedStoreFleet(tmp_path, n=3, node_ttl_s=0.4) as fleet:
        _use_fleet(monkeypatch, fleet)
        tree = _tree(leaves=6, seed=2)
        ds.put("heal/ckpt", tree, store_url=fleet.urls[0])
        fleet.stop_node(2)
        # first sweep starts every survivor's death clock for node2
        for url in fleet.urls[:2]:
            requests.post(f"{url}/scrub/run", timeout=60)
        time.sleep(0.5)                      # past the TTL
        for url in fleet.urls[:2]:
            rep = requests.post(f"{url}/scrub/run", timeout=60).json()
            assert rep.get("still_under_replicated", 0) == 0
        for url in fleet.urls[:2]:
            s = requests.get(f"{url}/scrub/status", timeout=10).json()
            assert s["under_replicated"] == 0
            assert s["ring"]["down"], "dead node must be in the ring view"
        # every key is back at R=2 on the SURVIVORS
        for i in range(6):
            holders = _kv_copies(fleet, f"heal/ckpt/layers/w{i:02d}")
            assert len(holders) == 2 and fleet.urls[2] not in holders


def test_rm_deletes_from_every_replica(tmp_path, monkeypatch):
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        ds.put("gone/ckpt", {"w": np.ones(8, np.float32)},
               store_url=fleet.urls[0])
        assert ds.rm("gone/ckpt", store_url=fleet.urls[0])
        for url in fleet.urls:
            r = requests.get(f"{url}/kv/gone/ckpt/w",
                             headers={ring.REPLICATED_HEADER: "1"},
                             timeout=10)
            assert r.status_code == 404
        assert ds.ls("gone/", store_url=fleet.urls[0]) == []


def test_push_pull_tree_survive_node_stop(tmp_path, monkeypatch):
    from kubetorch_tpu.data_store.sync import pull_tree, push_tree

    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        proj = tmp_path / "proj"
        proj.mkdir()
        for i in range(6):
            (proj / f"mod{i}.py").write_text(f"x = {i}\n" * 50)
        stats = push_tree(fleet.urls[0], "code/app", str(proj))
        assert stats["uploaded"] == 6
        fleet.stop_node(0)                   # kill a replica (and the seed)
        dest = tmp_path / "dest"
        out = pull_tree(fleet.urls[0], "code/app", str(dest))
        assert out["fetched"] == 6
        for i in range(6):
            assert (dest / f"mod{i}.py").read_text() == f"x = {i}\n" * 50


# ---------------------------------------------------------------------------
# Checkpoint markers: quorum reads across the ring
# ---------------------------------------------------------------------------


def test_checkpoint_marker_quorum_and_restore_with_dead_replica(
        tmp_path, monkeypatch):
    """Elastic-resume integration (light): a committed checkpoint on the
    ring restores bit-exact — fingerprint-matched — when one replica
    holding checkpoint state (the MARKER's primary, the worst case) is
    dead at restore time."""
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        c = ck.Checkpointer("job/ring", store_url=fleet.urls[0])
        tree = {"w": np.arange(16.0), "b": np.ones(4)}
        c.save(tree, 1)
        tree["w"] = tree["w"] + 1
        c.save(tree, 2)
        marker_key = "job/ring/__kt_commit__"
        primary = ring.ring_for(fleet.urls[0]).nodes_for(marker_key)[0]
        fleet.stop_node(fleet.urls.index(primary))
        ring.reset_rings()
        c2 = ck.Checkpointer("job/ring", store_url=fleet.urls[0])
        assert c2.last_committed_step == 2
        restored, step = c2.restore()
        assert step == 2
        assert ck.tree_fingerprint(restored) == ck.tree_fingerprint(tree)


def test_marker_quorum_read_prefers_newest_copy(tmp_path, monkeypatch):
    """A replica that missed the last marker write (down, now back) must
    never win the quorum read: newest stored_at wins."""
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        key = "stale/marker/__kt_commit__"
        ds.put_json(key, {"step": 1, "slot": 0}, store_url=fleet.urls[0])
        time.sleep(0.02)
        # overwrite on ONE replica only (simulates the survivor that took
        # the newer write while its sibling was down)
        holders = _kv_copies(fleet, key)
        assert len(holders) >= 2
        data = json.dumps({"step": 7, "slot": 1}).encode()
        meta = {"kind": "json",
                "blake2b": hashlib.blake2b(data, digest_size=20).hexdigest()}
        r = requests.put(f"{holders[0]}/kv/{quote(key, safe='/')}",
                         data=data,
                         headers={"X-KT-Meta": json.dumps(meta),
                                  ring.REPLICATED_HEADER: "1"}, timeout=30)
        assert r.status_code == 200
        got = ds.get_json(key, store_url=fleet.urls[0], quorum=True)
        assert got == {"step": 7, "slot": 1}


# ---------------------------------------------------------------------------
# Chaos acceptance: SIGKILL mid-push / mid-pull, zero client-visible failures
# ---------------------------------------------------------------------------


def _wait_scrub_heals(fleet, live_idx, deadline_s=60.0):
    """Drive /scrub/run on the survivors until under_replicated hits 0."""
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        ok = True
        for i in live_idx:
            rep = requests.post(f"{fleet.urls[i]}/scrub/run",
                                timeout=120).json()
            if rep.get("still_under_replicated", 0):
                ok = False
        if ok:
            statuses = [requests.get(f"{fleet.urls[i]}/scrub/status",
                                     timeout=10).json() for i in live_idx]
            if all(s["under_replicated"] == 0 for s in statuses):
                return statuses
        time.sleep(0.2)
    raise AssertionError("re-replication did not converge")


@pytest.mark.slow
def test_sigkill_store_node_mid_put_and_mid_pull_zero_failures(
        tmp_path, monkeypatch):
    """THE acceptance scenario. 3-node subprocess ring (R=2, W=2):

    1. node 1 is armed to SIGKILL itself on its 2nd client request — it
       dies MID multi-leaf put; the put completes with zero errors.
    2. every leaf reads back hash-verified (through ring failover).
    3. a tree push/pull with node 2 killed mid-pull also completes.
    4. once the dead node is past its TTL, /scrub/run re-replicates its
       keys: /scrub/status shows under_replicated == 0 and every key is
       on 2 live nodes again.
    5. kt_store_failovers_total incremented client-side throughout.
    """
    from kubetorch_tpu.data_store.sync import pull_tree, push_tree

    with SubprocessStoreFleet(
            tmp_path, n=3, node_ttl_s=0.5,
            chaos={1: "kill-store-node:9@1"}) as fleet:
        _use_fleet(monkeypatch, fleet)
        monkeypatch.setenv("KT_STORE_CONCURRENCY", "1")
        fail_before = sum(ring._FAILOVERS.value(kind=k)
                          for k in ("connect", "status", "breaker"))
        tree = _tree(leaves=24, seed=7)
        stats = ds.put("chaos/ckpt", tree, store_url=fleet.urls[0])
        assert stats["leaves"] == 24, "put must succeed despite the kill"
        assert fleet.wait_node_dead(1), \
            "node1 should have SIGKILLed itself mid-put"
        # hash-verified read-back of every leaf (fetch() verifies against
        # the index's blake2b; a corrupt or torn leaf would raise typed)
        out = ds.get("chaos/ckpt", store_url=fleet.urls[0])
        for name, arr in tree["layers"].items():
            np.testing.assert_array_equal(out["layers"][name], arr)
        fails_after = sum(ring._FAILOVERS.value(kind=k)
                          for k in ("connect", "status", "breaker"))
        assert fails_after > fail_before, \
            "the absorbed node loss must be visible in kt_store_failovers"

        # mid-pull loss: push a tree, then node 2 dies while we pull it
        proj = tmp_path / "proj"
        proj.mkdir()
        for i in range(8):
            (proj / f"f{i}.bin").write_bytes(os.urandom(4096) * 8)
        push_tree(fleet.urls[0], "chaos/code", str(proj))
        fleet.kill_node(2)
        dest = tmp_path / "dest"
        res = pull_tree(fleet.urls[0], "chaos/code", str(dest))
        assert res["files"] == 8
        for i in range(8):
            assert (dest / f"f{i}.bin").read_bytes() == \
                (proj / f"f{i}.bin").read_bytes()

        # restart node 2 (its disk survived; node 1 stays dead past TTL).
        # Depending on WHEN the kill landed, write-time ownership handoff
        # may already have placed every put key on the survivors — so also
        # plant a single-copy key (internal PUT to one node only): the
        # sweep MUST find it under-replicated and push its second copy.
        fleet.chaos.pop(1, None)
        fleet.start_node(2)
        lone_key = "chaos/lonely"
        lone = b"only one copy of me exists"
        meta = {"blake2b": hashlib.blake2b(lone, digest_size=20).hexdigest()}
        r = requests.put(f"{fleet.urls[0]}/kv/{quote(lone_key, safe='/')}",
                         data=lone,
                         headers={"X-KT-Meta": json.dumps(meta),
                                  ring.REPLICATED_HEADER: "1"}, timeout=30)
        assert r.status_code == 200
        assert _kv_copies(fleet, lone_key) == [fleet.urls[0]]
        time.sleep(0.6)                      # let node1 age past its TTL
        statuses = _wait_scrub_heals(fleet, live_idx=(0, 2))
        assert all(s["under_replicated"] == 0 for s in statuses)
        assert any(s["re_replicated"] > 0 for s in statuses), \
            "the under-replicated key must have been re-replicated"
        assert len(_kv_copies(fleet, lone_key)) == 2
        for i in range(24):
            holders = _kv_copies(fleet, f"chaos/ckpt/layers/w{i:02d}")
            assert len(holders) >= 2 and fleet.urls[1] not in holders, \
                f"leaf w{i:02d} must be back at R=2 on live nodes"


@pytest.mark.slow
@pytest.mark.elastic
def test_elastic_resume_with_checkpoint_on_ring_and_dead_replica(
        tmp_path, monkeypatch):
    """Acceptance: PR 6's kill-rank → N-1 resume scenario, unchanged —
    except the checkpoint lives on a 3-node ring and one replica holding
    checkpoint blobs is DEAD at restore time. The survivor must resume
    from the committed checkpoint through ring failover."""
    import asyncio

    from kubetorch_tpu.parallel.mesh import DistributedConfig
    from kubetorch_tpu.resources.pointers import Pointers
    from kubetorch_tpu.serving.spmd_supervisor import SPMDSupervisor

    assets = os.path.join(os.path.dirname(__file__), "assets")
    with ThreadedStoreFleet(tmp_path, n=3) as fleet:
        _use_fleet(monkeypatch, fleet)
        key = "elastic/ring-kill"
        monkeypatch.setenv("KT_CHAOS", "kill-rank:9@2")
        monkeypatch.setenv("KT_CHAOS_RANK", "1")
        monkeypatch.setenv("KT_WATCHDOG_INTERVAL_S", "0.25")
        monkeypatch.setenv("KT_RESTART_BUDGET", "3")
        monkeypatch.setenv("KT_RESTART_WINDOW_S", "300")
        monkeypatch.setenv("KT_RESTART_BACKOFF_BASE_S", "0.01")
        monkeypatch.setenv("KT_RESTART_BACKOFF_MAX_S", "0.01")
        monkeypatch.setenv("LOCAL_IPS", "127.0.0.1")
        monkeypatch.setenv("POD_IP", "127.0.0.1")
        cfg = DistributedConfig(
            distribution_type="spmd", workers=1, procs_per_worker=2,
            elastic={"max_resumes": 2})
        sup = SPMDSupervisor(
            Pointers(project_root=assets, module_name="payloads",
                     file_path="payloads.py",
                     cls_or_fn_name="ElasticTrainer"),
            {"args": [fleet.urls[0], key]}, cfg,
            service_name="t-ring-elastic", namespace="default")
        sup.setup()
        try:
            async def go():
                r1 = await sup.call("step", [], {}, timeout=120)
                assert len(r1) == 2
                r2 = await sup.call("step", [], {}, timeout=120)
                assert len(r2) == 2
                # the checkpoint for step 2 is committed on the ring —
                # NOW kill the replica holding its commit marker, then
                # let the chaos kill-rank fire mid-step-3: the elastic
                # resume must restore through ring failover
                marker = f"{key}/__kt_commit__"
                primary = ring.ring_for(
                    fleet.urls[0]).nodes_for(marker)[0]
                fleet.stop_node(fleet.urls.index(primary))
                return await sup.call("step", [], {}, timeout=None)

            r3 = asyncio.run(go())
            assert len(r3) == 1, "fan-out should have shrunk to 1 rank"
            out = r3[0]
            assert out["resumed_from"] is not None, \
                "survivor should have resumed from the ring checkpoint"
            assert out["step"] == out["resumed_from"] + 1
            assert sup.elastic.resumes == 1
            # the resumed state hash-matches a clean ring reload
            ring.reset_rings()
            reloaded, step = ck.Checkpointer(
                key, store_url=fleet.urls[0]).restore()
            assert step == out["step"]
            assert ck.tree_fingerprint(reloaded) == out["fingerprint"]
        finally:
            sup.cleanup()


# ---------------------------------------------------------------------------
# ISSUE 10: streamed proxy relay (O(chunk) RSS) + /kv/diff compression
# ---------------------------------------------------------------------------


def _vmrss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    raise RuntimeError("no VmRSS")


@pytest.mark.slow
def test_proxied_get_streams_with_o_chunk_rss(tmp_path):
    """A ring-wide proxy GET must RELAY, not buffer (ISSUE 10): node B
    serving a blob that lives only on node A holds O(chunk) RSS while the
    whole blob moves — the PR 1 streaming-PUT discipline, now symmetric.
    Before the StreamResponse relay, this held the full body in RAM
    (``await r.read()``), so the assertion below failed by ~blob size."""
    size = 64 << 20
    blob = os.urandom(1 << 20) * 64          # 64 MB, two nodes, R=1
    h = hashlib.blake2b(blob, digest_size=20).hexdigest()
    with SubprocessStoreFleet(tmp_path, n=2, replication=1,
                              write_quorum=1) as fleet:
        # land the blob on node 0 ONLY (internal header: no replication)
        r = requests.put(f"{fleet.urls[0]}/blob/{h}", data=blob,
                         headers={"X-KT-Replicated": "1"}, timeout=120)
        assert r.status_code == 200
        proxy_pid = fleet.procs[1].pid
        base_kb = _vmrss_kb(proxy_pid)
        # GET via node 1 → local miss → streamed relay from node 0
        peak_kb, got = base_kb, hashlib.blake2b(digest_size=20)
        read = 0
        with requests.get(f"{fleet.urls[1]}/blob/{h}", stream=True,
                          timeout=120) as resp:
            assert resp.status_code == 200
            for chunk in resp.iter_content(1 << 20):
                got.update(chunk)
                read += len(chunk)
                peak_kb = max(peak_kb, _vmrss_kb(proxy_pid))
        assert read == size and got.hexdigest() == h   # bit-exact relay
        delta_mb = (peak_kb - base_kb) / 1024.0
        assert delta_mb < size / (1 << 20) / 2, \
            f"proxy node RSS grew {delta_mb:.0f} MB during a " \
            f"{size >> 20} MB proxied GET — the relay is buffering"


def test_kv_diff_body_compression_negotiated(tmp_path):
    """/kv/diff speaks zlib (zstd when available) both ways, negotiated
    per request; clients that send no codec headers get the exact legacy
    wire shape."""
    import zlib

    from kubetorch_tpu.data_store.store_server import create_store_app

    with ThreadedAiohttpServer(
            lambda: create_store_app(str(tmp_path / "s"))) as srv:
        url = srv.url
        body = b"\x01\x02\x03"
        h = hashlib.blake2b(body, digest_size=20).hexdigest()
        assert requests.put(f"{url}/kv/comp/a", data=body,
                            timeout=30).status_code == 200
        # big key table: compresses on the way in, reply compresses too
        keys = {f"comp/missing-{i:04d}": "f" * 40 for i in range(200)}
        keys["comp/a"] = h
        payload = json.dumps({"keys": keys}).encode()
        comp = zlib.compress(payload, 3)
        assert len(comp) < len(payload) // 2
        r = requests.post(
            f"{url}/kv/diff", data=comp,
            headers={"Content-Type": "application/json",
                     "Content-Encoding": "zlib",
                     "Accept-Encoding": "zlib"}, timeout=30)
        assert r.status_code == 200
        assert r.headers.get("Content-Encoding") == "zlib"
        missing = json.loads(zlib.decompress(r.content))["missing"]
        assert "comp/a" not in missing and len(missing) == 200
        # legacy client: no codec headers → plain JSON both ways
        r = requests.post(f"{url}/kv/diff",
                          json={"keys": {"comp/a": h, "comp/nope": h}},
                          headers={"Accept-Encoding": "identity"},
                          timeout=30)
        assert r.status_code == 200
        assert "Content-Encoding" not in r.headers
        assert r.json()["missing"] == ["comp/nope"]
        # garbage compressed body → clean 400, not a 500
        r = requests.post(f"{url}/kv/diff", data=b"not zlib",
                          headers={"Content-Encoding": "zlib"}, timeout=30)
        assert r.status_code == 400


def test_kv_diff_client_helper_round_trips_compressed(tmp_path):
    """The put/delta client path itself negotiates compression: a warm
    re-put over a >COMPRESS_MIN_BYTES key table still skips every leaf."""
    from kubetorch_tpu.data_store.store_server import create_store_app

    with ThreadedAiohttpServer(
            lambda: create_store_app(str(tmp_path / "s2"))) as srv:
        rng = np.random.default_rng(7)
        tree = {"layer": {f"w{i:03d}": rng.standard_normal(16).astype(
            np.float32) for i in range(40)}}    # 40 keys → >1 KB table
        cold = ds.put("comptree/w", tree, store_url=srv.url)
        assert cold["skipped"] == 0
        warm = ds.put("comptree/w", tree, store_url=srv.url)
        assert warm["skipped"] == warm["leaves"] == 40
        assert warm["bytes"] == 0
        out = ds.get("comptree/w", store_url=srv.url)
        np.testing.assert_array_equal(out["layer"]["w000"],
                                      tree["layer"]["w000"])


def test_netpool_body_codecs_round_trip():
    data = json.dumps({"keys": {str(i): "a" * 40
                                for i in range(100)}}).encode()
    for coding in ("zlib",) + (("zstd",) if netpool._zstd() else ()):
        comp = netpool.compress_body(data, coding)
        assert len(comp) < len(data)
        assert netpool.decompress_body(comp, coding) == data
    assert netpool.decompress_body(data, None) == data
    assert netpool.best_coding("zlib, gzip") == "zlib"
    assert netpool.best_coding("identity") is None
    assert netpool.best_coding(None) is None
