"""External data tunnel (round-2 VERDICT next #5 / missing #51).

Reference: ``data_store/websocket_tunnel.py`` — rsync from a laptop without
kubectl. Here the store speaks plain HTTP, so the tunnel is the controller's
``/controller/store`` relay; the client falls back to it when the in-cluster
store URL is unreachable. The e2e below round-trips kt.put/get using ONLY
the controller URL."""

import asyncio
import threading

import numpy as np
import pytest

from kubetorch_tpu.config import config, reset_config

pytestmark = pytest.mark.level("unit")


class _Stack:
    """Store app + controller app on real TCP ports (plain requests reaches
    them, unlike aiohttp TestClient)."""

    def __init__(self, tmp):
        self.tmp = tmp
        self.loop = asyncio.new_event_loop()
        self.store_url = None
        self.controller_url = None
        self._started = threading.Event()

    def start(self):
        def run():
            asyncio.set_event_loop(self.loop)
            self.loop.run_until_complete(self._setup())
            self._started.set()
            self.loop.run_forever()

        threading.Thread(target=run, daemon=True).start()
        assert self._started.wait(15)
        return self

    async def _setup(self):
        from aiohttp import web

        from kubetorch_tpu.controller.app import (ControllerState,
                                                  create_controller_app)
        from kubetorch_tpu.data_store.store_server import create_store_app

        store_runner = web.AppRunner(create_store_app(str(self.tmp / "store")))
        await store_runner.setup()
        store_site = web.TCPSite(store_runner, "127.0.0.1", 0)
        await store_site.start()
        sport = store_site._server.sockets[0].getsockname()[1]
        self.store_url = f"http://127.0.0.1:{sport}"

        state = ControllerState()
        state.cluster_config["data_store_url"] = self.store_url
        ctl_runner = web.AppRunner(create_controller_app(state))
        await ctl_runner.setup()
        ctl_site = web.TCPSite(ctl_runner, "127.0.0.1", 0)
        await ctl_site.start()
        cport = ctl_site._server.sockets[0].getsockname()[1]
        self.controller_url = f"http://127.0.0.1:{cport}"

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)


@pytest.fixture()
def stack(tmp_path):
    s = _Stack(tmp_path).start()
    yield s
    s.stop()


def test_put_get_through_controller_only(stack, monkeypatch):
    """Direct store URL unreachable (the laptop case) → put/get round-trip
    rides the controller relay."""
    from kubetorch_tpu.data_store import commands

    monkeypatch.setenv("KT_API_URL", stack.controller_url)
    # the in-cluster DNS name never resolves from outside
    monkeypatch.setenv("KT_DATA_STORE_URL", "http://127.0.0.1:9")  # closed port
    reset_config()
    commands._REACHABLE_CACHE.clear()
    try:
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        commands.put("tunnel-test/x", arr)
        out = commands.get("tunnel-test/x")
        np.testing.assert_array_equal(np.asarray(out), arr)

        used, expires = commands._REACHABLE_CACHE["http://127.0.0.1:9"]
        assert used == f"{stack.controller_url}/controller/store"
        assert expires is not None   # tunnel verdicts expire (recovery path)
    finally:
        reset_config()
        commands._REACHABLE_CACHE.clear()


def test_direct_store_stays_direct(stack, monkeypatch):
    """In-cluster/local clients pass the probe and never pay the hop."""
    from kubetorch_tpu.data_store import commands

    monkeypatch.setenv("KT_DATA_STORE_URL", stack.store_url)
    monkeypatch.delenv("KT_API_URL", raising=False)
    reset_config()
    commands._REACHABLE_CACHE.clear()
    try:
        assert commands._store_url() == stack.store_url
        # a caller-NAMED store is never rerouted, reachable or not
        assert commands._store_url("http://127.0.0.1:9") == "http://127.0.0.1:9"
    finally:
        reset_config()
        commands._REACHABLE_CACHE.clear()


def test_tunnel_code_push(stack, monkeypatch, tmp_path):
    """Code sync (the 1-2s loop) also works from outside: push_tree/pull_tree
    against the relay URL."""
    from kubetorch_tpu.data_store.sync import pull_tree, push_tree

    src = tmp_path / "proj"
    src.mkdir()
    (src / "main.py").write_text("print('hi')\n")
    (src / "pkg").mkdir()
    (src / "pkg" / "__init__.py").write_text("")

    tunnel = f"{stack.controller_url}/controller/store"
    stats = push_tree(tunnel, "__code__/tunnel-proj", str(src))
    assert stats["files"] == 2

    dest = tmp_path / "out"
    pull_tree(tunnel, "__code__/tunnel-proj", str(dest))
    assert (dest / "main.py").read_text() == "print('hi')\n"
