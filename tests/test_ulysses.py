"""Ulysses all-to-all sequence parallelism vs full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.level("release")  # jit-heavy matrix: full tier only

from kubetorch_tpu.models.llama import _xla_attention
from kubetorch_tpu.parallel.mesh import build_mesh
from kubetorch_tpu.parallel.ulysses import ulysses_attention_sharded


def _qkv(b=8, s=64, n=8, nkv=4, hd=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (b, s, n, hd)),
            jax.random.normal(ks[1], (b, s, nkv, hd)),
            jax.random.normal(ks[2], (b, s, nkv, hd)))


@pytest.mark.parametrize("ctx", [2, 4])
def test_ulysses_matches_full(cpu_mesh_devices, ctx):
    mesh = build_mesh({"context": ctx, "data": 8 // ctx})
    q, k, v = _qkv()
    out = jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh))(q, k, v)
    ref = _xla_attention(q, k, v, q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_grads_match(cpu_mesh_devices):
    mesh = build_mesh({"context": 4, "data": 2})
    q, k, v = _qkv(s=32)
    g_u = jax.grad(lambda q, k, v: jnp.sum(
        ulysses_attention_sharded(q, k, v, mesh) ** 2), (0, 1, 2))(q, k, v)
    g_r = jax.grad(lambda q, k, v: jnp.sum(
        _xla_attention(q, k, v, q.shape[-1] ** -0.5) ** 2), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_u, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-4, err_msg=f"d{name}")


def test_ulysses_degree_must_divide_heads(cpu_mesh_devices):
    mesh = build_mesh({"context": 8})
    q, k, v = _qkv(n=8, nkv=4)   # nkv=4 not divisible by C=8
    with pytest.raises(ValueError, match="must divide"):
        jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh))(q, k, v)


def test_llama_with_ulysses(cpu_mesh_devices):
    """Full model forward with attn_impl='ulysses' matches the xla path."""
    from kubetorch_tpu.models.llama import LlamaConfig, llama_forward, llama_init
    from kubetorch_tpu.parallel.mesh_context import use_mesh

    cfg = LlamaConfig.tiny(attn_impl="ulysses", dtype=jnp.float32, remat=False)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
    ref = llama_forward(params, tokens, LlamaConfig.tiny(
        attn_impl="xla", dtype=jnp.float32, remat=False))
    mesh = build_mesh({"context": 2, "data": 4})
    with use_mesh(mesh):
        out = jax.jit(lambda p, t: llama_forward(p, t, cfg))(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
