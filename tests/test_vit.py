"""ViT encoder family: forward shapes, training, mesh-sharded parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.level("release")  # jit-heavy matrix: full tier only

from kubetorch_tpu.models.vit import (VitConfig, patchify, vit_forward,
                                      vit_init, vit_loss)

CFG = VitConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False)


def _batch(key, n=4):
    kx, ky = jax.random.split(jax.random.PRNGKey(key))
    images = jax.random.normal(kx, (n, CFG.image_size, CFG.image_size,
                                    CFG.channels))
    labels = jax.random.randint(ky, (n,), 0, CFG.n_classes)
    return images, labels


def test_patchify_preserves_pixels():
    images, _ = _batch(0, n=2)
    patches = patchify(images, CFG)
    assert patches.shape == (2, CFG.n_patches, CFG.patch_dim)
    # first patch is the top-left p×p block, row-major
    p = CFG.patch_size
    np.testing.assert_array_equal(
        np.asarray(patches[0, 0]),
        np.asarray(images[0, :p, :p, :]).reshape(-1))


def test_forward_shape_and_determinism():
    params = vit_init(jax.random.PRNGKey(0), CFG)
    images, _ = _batch(1)
    logits = vit_forward(params, images, CFG)
    assert logits.shape == (4, CFG.n_classes)
    assert logits.dtype == jnp.float32
    jitted = jax.jit(lambda p, x: vit_forward(p, x, CFG))(params, images)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(logits),
                               rtol=1e-5, atol=1e-5)


def test_train_decreases_loss():
    import optax

    params = vit_init(jax.random.PRNGKey(0), CFG)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    images, labels = _batch(2, n=8)

    @jax.jit
    def step(params, opt_state):
        loss, g = jax.value_and_grad(vit_loss)(params, images, labels, CFG)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_remat_matches_no_remat():
    cfg_r = VitConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=True)
    params = vit_init(jax.random.PRNGKey(0), CFG)
    images, labels = _batch(3)
    g1 = jax.grad(vit_loss)(params, images, labels, CFG)
    g2 = jax.grad(vit_loss)(params, images, labels, cfg_r)
    np.testing.assert_allclose(np.asarray(g1["layers"]["wqkv"]),
                               np.asarray(g2["layers"]["wqkv"]),
                               rtol=1e-5, atol=1e-5)


def test_sharded_train_step_matches_single_device(cpu_mesh_devices):
    """dp×fsdp×tp mesh via VIT_RULES: first-step loss equals unsharded."""
    import optax

    from kubetorch_tpu.parallel.mesh import MeshSpec, build_mesh
    from kubetorch_tpu.parallel.sharding import VIT_RULES
    from kubetorch_tpu.train import init_train_state, make_train_step

    mesh = build_mesh(MeshSpec(data=2, fsdp=2, tensor=2),
                      devices=jax.devices()[:8])
    params = vit_init(jax.random.PRNGKey(0), CFG)
    images, labels = _batch(4, n=8)
    ref_loss = float(vit_loss(params, images, labels, CFG))

    from jax.sharding import NamedSharding, PartitionSpec as P

    opt = optax.adamw(1e-3)
    state = init_train_state(params, opt)
    step = make_train_step(lambda p, x, y: vit_loss(p, x, y, CFG),
                           optimizer=opt, mesh=mesh, rules=VIT_RULES)
    state = step.shard_state(state)
    batch_sh = NamedSharding(mesh, P(("data", "fsdp")))
    batch = {"tokens": jax.device_put(images, batch_sh),
             "targets": jax.device_put(labels, batch_sh)}
    state, metrics = step(state, batch)
    assert np.isclose(float(metrics["loss"]), ref_loss, rtol=1e-4)


def test_vit_pipeline_matches_sequential(cpu_mesh_devices):
    """ViT encoder layers pipeline over data×fsdp×pipe (ZeRO-3 in-stage),
    GPipe and interleaved schedules both matching the sequential model."""
    from kubetorch_tpu.parallel.mesh import MeshSpec, build_mesh
    from kubetorch_tpu.parallel.pipeline import (vit_forward_pipelined,
                                                 vit_loss_pipelined,
                                                 vit_pipeline_place)

    cfg = VitConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False,
                         n_layers=8)
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, pipe=2),
                      devices=jax.devices()[:8])
    params = vit_init(jax.random.PRNGKey(0), cfg)
    images, labels = _batch(7, n=8)
    ref = vit_forward(params, images, cfg)

    placed = vit_pipeline_place(params, mesh)
    out = jax.jit(lambda p, x: vit_forward_pipelined(
        p, x, cfg, mesh, n_microbatches=2))(placed, images)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)

    g_ref = jax.grad(vit_loss)(params, images, labels, cfg)
    g = jax.jit(jax.grad(lambda p, x, y: vit_loss_pipelined(
        p, x, y, cfg, mesh, n_microbatches=2)))(placed, images, labels)
    np.testing.assert_allclose(np.asarray(g["layers"]["wqkv"]),
                               np.asarray(g_ref["layers"]["wqkv"]),
                               rtol=5e-4, atol=5e-4)

    placed2 = vit_pipeline_place(params, mesh, n_virtual=2)
    out2 = jax.jit(lambda p, x: vit_forward_pipelined(
        p, x, cfg, mesh, n_microbatches=2, n_virtual=2))(placed2, images)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)
    # interleaved grads: undo the (V, P, lpc) layout and compare
    g2 = jax.jit(jax.grad(lambda p, x, y: vit_loss_pipelined(
        p, x, y, cfg, mesh, n_microbatches=2, n_virtual=2)))(
        placed2, images, labels)
    gw = np.asarray(g2["layers"]["wqkv"])
    recon = np.concatenate([gw[v, p] for v in range(2) for p in range(2)],
                           axis=0)
    np.testing.assert_allclose(recon, np.asarray(g_ref["layers"]["wqkv"]),
                               rtol=5e-4, atol=5e-4)


def test_vit_pipeline_tp_guard(cpu_mesh_devices):
    from kubetorch_tpu.parallel.mesh import MeshSpec, build_mesh
    from kubetorch_tpu.parallel.pipeline import (vit_forward_pipelined,
                                                 vit_pipeline_place)

    cfg = VitConfig.tiny(attn_impl="xla", dtype=jnp.float32, remat=False,
                         n_layers=8)
    mesh = build_mesh(MeshSpec(pipe=2, tensor=2), devices=jax.devices()[:4])
    placed = vit_pipeline_place(vit_init(jax.random.PRNGKey(0), cfg), mesh)
    with pytest.raises(ValueError, match="tensor"):
        vit_forward_pipelined(placed, jnp.zeros((4, 32, 32, 3)), cfg, mesh)
