"""Volume lifecycle on both backends (round-2 VERDICT next #6 / weak #3).

Reference model: ``resources/volumes/volume.py`` — create/exists/delete(wait)
/from_name round-trip, storage-class resolution, scratch-pod ssh. The PVC
delete must ride the controller's kind-aware object store, never the
workload sweep.
"""

import json
import os
import stat
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "assets"))

import kubetorch_tpu as kt
from kubetorch_tpu.resources.volume import Volume

import payloads  # noqa: F401  (keeps module registered for e2e reloads)

pytestmark = pytest.mark.level("unit")

SHIM = os.path.join(os.path.dirname(__file__), "assets", "fake_kubectl.py")


class TestVolumeUnit:
    def test_manifest(self):
        v = Volume("scratch", size="50Gi", mount_path="/scratch",
                   storage_class="fast")
        m = v.manifest("ns1")
        assert m["kind"] == "PersistentVolumeClaim"
        assert m["spec"]["resources"]["requests"]["storage"] == "50Gi"
        assert m["spec"]["storageClassName"] == "fast"
        assert v.mount_spec() == {"name": "scratch", "claim": "scratch",
                                  "mount_path": "/scratch"}

    def test_rwx_resolution_picks_capable_class(self, monkeypatch):
        monkeypatch.setattr(Volume, "storage_classes", classmethod(
            lambda cls: [
                {"name": "pd", "default": True,
                 "provisioner": "pd.csi.storage.gke.io"},
                {"name": "share", "default": False,
                 "provisioner": "filestore.csi.storage.gke.io"}]))
        v = Volume("shared", access_mode="ReadWriteMany")
        assert v._resolve_rwx_class() == "share"

    def test_rwx_resolution_errors_without_capable_class(self, monkeypatch):
        monkeypatch.setattr(Volume, "storage_classes", classmethod(
            lambda cls: [{"name": "pd", "default": True,
                          "provisioner": "pd.csi.storage.gke.io"}]))
        with pytest.raises(ValueError, match="No RWX-capable"):
            Volume("shared", access_mode="ReadWriteMany")._resolve_rwx_class()

    def test_scratch_pod_cmd(self):
        v = Volume("cache", mount_path="/kt/cache")
        manifest = v.scratch_pod_manifest("ubuntu:22.04")
        spec = manifest["spec"]
        assert spec["volumes"][0]["persistentVolumeClaim"]["claimName"] == "cache"
        assert spec["containers"][0]["volumeMounts"][0]["mountPath"] == "/kt/cache"
        cmd = v._ssh_cmd("ubuntu:22.04", namespace="ns2")
        assert cmd[:2] == ["kubectl", "run"]
        assert "--overrides" in cmd and "ns2" in cmd


class TestLocalBackendVolumes:
    def test_pvc_maps_to_host_dir_and_pod_env(self, tmp_path):
        from kubetorch_tpu.controller.backends import LocalBackend
        from kubetorch_tpu.provisioning.manifests import (
            build_deployment_manifest, build_pod_template)

        be = LocalBackend("http://127.0.0.1:1",
                          secrets_dir=str(tmp_path / "secrets"),
                          volumes_dir=str(tmp_path / "volumes"))
        out = be.apply("ns1", "scratch",
                       Volume("scratch").manifest("ns1"), {})
        assert out == {"kind": "PersistentVolumeClaim", "stored": True}
        vdir = tmp_path / "volumes" / "ns1__scratch"
        assert vdir.is_dir()
        assert be.get_object("PersistentVolumeClaim", "ns1", "scratch")

        pod = build_pod_template(
            "web", "img", {},
            volumes=[Volume("scratch", mount_path="/mnt/scratch").mount_spec()])
        env = be._volume_env("ns1", build_deployment_manifest(
            "web", "ns1", 1, pod))
        assert env["KT_VOLUME_SCRATCH"] == str(vdir)

        assert be.delete_object("PersistentVolumeClaim", "ns1", "scratch")
        assert not vdir.exists()
        assert be.get_object("PersistentVolumeClaim", "ns1", "scratch") is None


@pytest.fixture()
def shim(tmp_path, monkeypatch):
    os.chmod(SHIM, os.stat(SHIM).st_mode | stat.S_IXUSR | stat.S_IXGRP)
    monkeypatch.setenv("KT_KUBECTL_SHIM_DIR", str(tmp_path))
    return tmp_path


class TestK8sBackendVolumes:
    def test_pvc_crud_round_trip(self, shim):
        from kubetorch_tpu.controller.backends import KubernetesBackend

        be = KubernetesBackend(kubectl=SHIM)
        v = Volume("data", size="20Gi", storage_class="filestore-rwx")
        be.apply("ns1", "data", v.manifest("ns1"), {})

        obj = be.get_object("PersistentVolumeClaim", "ns1", "data")
        assert obj["spec"]["resources"]["requests"]["storage"] == "20Gi"
        assert be.get_object("PersistentVolumeClaim", "ns1", "nope") is None

        classes = be.storage_classes()
        assert {"name": "standard-rwo", "default": True,
                "provisioner": "pd.csi.storage.gke.io"} in classes

        assert be.delete_object("PersistentVolumeClaim", "ns1", "data") is True
        assert be.get_object("PersistentVolumeClaim", "ns1", "data") is None
        assert be.delete_object("PersistentVolumeClaim", "ns1", "data") is False


@pytest.mark.slow
@pytest.mark.level("minimal")
class TestVolumeE2E:
    def test_volume_lifecycle_through_controller(self):
        """create → from_name round-trip → pod writes into the backing dir →
        kind-aware delete (NOT delete_workload), all via the live local
        controller."""
        v = Volume("e2e-vol", size="1Gi", mount_path="/mnt/e2e-vol")
        v.create()
        try:
            assert v.exists()
            again = Volume.from_name("e2e-vol")
            assert again.size == "1Gi"

            f = kt.fn(write_marker)
            f.to(kt.Compute(cpus=1, volumes=[v]))
            try:
                path = f("e2e-vol", "hello-volume")
                assert path is not None
                with open(path) as fh:
                    assert fh.read() == "hello-volume"
            finally:
                f.teardown()
        finally:
            v.delete(wait=True, timeout=30)
        assert not v.exists()


def write_marker(vol_name, content):
    """Runs in the pod: write into the volume's backing dir (local pods see
    it via KT_VOLUME_<NAME>)."""
    root = os.environ.get("KT_VOLUME_" + vol_name.upper().replace("-", "_"))
    if root is None:
        return None
    path = os.path.join(root, "marker.txt")
    with open(path, "w") as fh:
        fh.write(content)
    return path
