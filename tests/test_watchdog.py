"""Worker liveness watchdog (ISSUE 3): a rank subprocess dying mid-call
surfaces as a typed ``WorkerDiedError`` bounded by the watchdog interval —
not the call timeout, and not a hang with ``timeout=None`` — the pool
self-heals within a sliding-window restart budget, and budget exhaustion is
a permanent typed failure that keeps ``/ready`` down.

Process-level deaths are injected deterministically with the chaos verb
``kill-rank:<sig>@<op-index>`` (the rank kills itself at a chosen call
index), so detection latency and restart cadence are assertable without
racing a real preemption.
"""

import asyncio
import os
import time
from types import SimpleNamespace

import pytest
import requests

pytestmark = pytest.mark.level("minimal")

from kubetorch_tpu.chaos import ChaosEngine, parse_spec, rank_kill_plan
from kubetorch_tpu.exceptions import (WorkerDiedError, package_exception,
                                      rehydrate_exception)
from kubetorch_tpu.resilience import RestartBudget
from kubetorch_tpu.resources.pointers import Pointers
from kubetorch_tpu.serving import watchdog as wd
from kubetorch_tpu.serving.process_pool import ProcessPool
from tests.assets.threaded_server import ThreadedAiohttpServer

ASSETS = os.path.join(os.path.dirname(__file__), "assets")


def _pointers(fn="sleeper"):
    return Pointers(project_root=ASSETS, module_name="payloads",
                    file_path="payloads.py", cls_or_fn_name=fn)


def _make_pool(monkeypatch, chaos, num_procs=1, framework="spmd",
               interval="0.25", budget="3", window="300"):
    monkeypatch.setenv("KT_CHAOS", chaos)
    monkeypatch.setenv("KT_WATCHDOG_INTERVAL_S", interval)
    monkeypatch.setenv("KT_RESTART_BUDGET", budget)
    monkeypatch.setenv("KT_RESTART_WINDOW_S", window)
    # near-zero respawn backoff: these tests assert detection latency, not
    # backoff pacing
    monkeypatch.setenv("KT_RESTART_BACKOFF_BASE_S", "0.01")
    monkeypatch.setenv("KT_RESTART_BACKOFF_MAX_S", "0.01")
    return ProcessPool(num_procs, framework, _pointers(), None)


def _wait_until(predicate, timeout=45.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# Death classification
# ---------------------------------------------------------------------------


def test_classify_death_taxonomy():
    assert wd.classify_death(None) == "Unknown"
    assert wd.classify_death(0) == "Exited"
    assert wd.classify_death(3) == "Crashed"
    assert wd.classify_death(-11) == "Crashed"   # SIGSEGV
    assert wd.classify_death(-6) == "Crashed"    # SIGABRT
    assert wd.classify_death(-9) == "Killed"
    assert wd.classify_death(-9, oom_evidence=True) == "OOMKilled"
    assert wd.classify_death(-15, draining=True) == "Evicted"
    assert wd.classify_death(-15, draining=False) == "Killed"


def test_classify_sigterm_uses_drain_flag_and_preemption_marker(monkeypatch):
    wd.set_draining()
    try:
        assert wd.classify_death(-15) == "Evicted"
        # a preemption marker outranks plain eviction (GKE spot reclaim)
        monkeypatch.setenv("KT_PREEMPTIBLE", "1")
        assert wd.classify_death(-15) == "Preempted"
    finally:
        wd.clear_draining()


def test_oom_evidence_from_cgroup_counter(tmp_path, monkeypatch):
    events = tmp_path / "memory.events"
    events.write_text("low 0\nhigh 4\noom 3\noom_kill 2\n")
    monkeypatch.setenv("KT_OOM_EVENTS_PATH", str(events))
    assert wd.read_oom_kill_count() == 2
    # baseline snapshotted at watchdog construction; a later increment is
    # the evidence that a SIGKILL was the kernel's OOM killer
    pool = ProcessPool(1, "spmd", None, None)
    events.write_text("low 0\nhigh 4\noom 5\noom_kill 3\n")
    fake = SimpleNamespace(exitcode=-9)
    err = pool.watchdog.death_error(0, fake)
    assert err.cause == "OOMKilled" and err.rank == 0 and err.exitcode == -9


def test_oom_counter_absent_is_none(monkeypatch):
    monkeypatch.setenv("KT_OOM_EVENTS_PATH", "/nonexistent/memory.events")
    assert wd.read_oom_kill_count() is None


def test_worker_died_error_rehydrates():
    out = rehydrate_exception(package_exception(WorkerDiedError(
        "rank 2 gone", cause="Preempted", rank=2, exitcode=-15)))
    assert isinstance(out, WorkerDiedError)
    assert out.cause == "Preempted" and out.preempted
    assert out.rank == 2 and out.exitcode == -15


# ---------------------------------------------------------------------------
# Restart budget (sliding window)
# ---------------------------------------------------------------------------


def test_restart_budget_window_regenerates():
    now = [0.0]
    b = RestartBudget(2, window_s=10.0, clock=lambda: now[0])
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()          # exhausted inside the window
    now[0] = 11.0                        # first acquisition ages out
    assert b.remaining == 2
    assert b.try_acquire()
    assert b.state()["used"] == 1


def test_restart_budget_zero_disables_self_heal():
    b = RestartBudget(0, window_s=10.0)
    assert not b.try_acquire()


# ---------------------------------------------------------------------------
# kill-rank chaos verb
# ---------------------------------------------------------------------------


def test_kill_rank_parse_and_plan():
    faults = parse_spec("kill-rank:9@2,kill-rank:SEGV@5,kill-rank")
    kinds = [(f.kind, f.signal_no, f.op_index) for f in faults]
    assert kinds == [("kill-rank", 9, 2), ("kill-rank", 11, 5),
                     ("kill-rank", 9, 0)]
    assert rank_kill_plan("kill-rank:KILL@1,503,reset") == {1: 9}
    assert rank_kill_plan("reset,503") == {}
    assert rank_kill_plan("") == {}


def test_kill_rank_invisible_to_http_engine():
    """kill-rank is process-level: the HTTP middleware schedule must skip
    it entirely — only the 503 remains."""
    engine = ChaosEngine(parse_spec("kill-rank:9@0,503"))
    assert len(engine.schedule) == 1 and engine.schedule[0].kind == "status"


def test_malformed_kill_rank_plan_is_empty_not_fatal():
    # a typo in the worker env must not become a spawn-time crash loop
    assert rank_kill_plan("kill-rank:NOTASIG@x") == {}


# ---------------------------------------------------------------------------
# Satellite fixes: submit race + cancel_pending without a loop
# ---------------------------------------------------------------------------


class _RacyWorker:
    """Claims to be alive, then fails the queue put — the race where the
    rank dies between the liveness check and worker.submit()."""

    alive = True
    in_warmup = False
    exitcode = -9

    def submit(self, req):
        raise OSError("handle is closed")

    def force_kill_if_alive(self):
        pass


def test_submit_race_raises_typed_and_pops_future():
    pool = ProcessPool(1, "spmd", None, None)
    pool.workers[0] = _RacyWorker()

    async def go():
        with pytest.raises(WorkerDiedError) as ei:
            await pool._submit(0, {"method": None, "args": [], "kwargs": {}},
                               None)
        return ei.value

    err = asyncio.run(go())
    assert err.rank == 0 and err.cause == "Killed"
    assert isinstance(err.__cause__, OSError)
    assert pool._futures == {}          # the registered future must not leak


def test_submit_to_dead_worker_raises_typed():
    pool = ProcessPool(1, "spmd", None, None)
    pool.workers[0] = SimpleNamespace(alive=False, exitcode=-11,
                                      in_warmup=False)

    async def go():
        with pytest.raises(WorkerDiedError) as ei:
            await pool._submit(0, {"method": None, "args": [], "kwargs": {}},
                               None)
        return ei.value

    assert asyncio.run(go()).cause == "Crashed"


def test_cancel_pending_without_loop_fails_futures_synchronously():
    """A pool that never served a call has ``_loop is None`` — shutdown must
    still fail registered futures instead of silently dropping them."""
    pool = ProcessPool(1, "spmd", None, None)
    loop = asyncio.new_event_loop()
    try:
        fut = loop.create_future()
        pool._futures["r0"] = (fut, 0)
        assert pool._loop is None
        pool.cancel_pending(RuntimeError("pool shutting down"))
        assert fut.done()
        assert isinstance(fut.exception(), RuntimeError)
        assert pool._futures == {}
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# The hang regression + self-heal (chaos acceptance)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_rank_killed_mid_call_raises_typed_promptly_and_pool_self_heals(
        monkeypatch):
    """THE acceptance scenario: a rank SIGKILLed mid-call with
    ``timeout=None`` raises ``WorkerDiedError`` (correct cause + rank) in
    under 2× the watchdog interval — previously this hung forever — then the
    pool auto-restarts within budget and the next call succeeds."""
    interval = 0.5
    pool = _make_pool(monkeypatch, "kill-rank:9@1", interval=str(interval))
    pool.start()
    try:
        async def go():
            assert await pool.call(0, None, [0.01], {}) == 0.01  # op 0: fine
            t0 = time.monotonic()
            with pytest.raises(WorkerDiedError) as ei:
                # op 1: SIGKILL lands mid-call; timeout=None means only the
                # watchdog can end this await
                await pool.call(0, None, [60], {}, timeout=None)
            detect = time.monotonic() - t0
            assert detect < 2 * interval, \
                f"death surfaced in {detect:.2f}s, want < {2 * interval}s"
            err = ei.value
            assert err.cause == "Killed" and err.rank == 0
            assert err.exitcode == -9

            # self-heal: watchdog respawns the rank within budget...
            assert _wait_until(
                lambda: pool.healthy and not pool.recovering), \
                "pool never healed"
            assert pool.watchdog.restarts == 1
            # ...and the next call succeeds (fresh worker: op index reset)
            assert await pool.call(0, None, [0.02], {}) == 0.02

        asyncio.run(go())
        # router hygiene: the dead worker's router thread must exit once its
        # queue drains — exactly one live router per live worker remains
        assert _wait_until(
            lambda: sum(t.is_alive() for t in pool._router_threads)
            == pool.num_procs, timeout=10), "dead worker's router still spinning"
        state = pool.watchdog.state_dict()
        assert state["restarts"] == 1 and not state["recovering"]
        assert state["recent_deaths"][-1]["cause"] == "Killed"
    finally:
        pool.shutdown()


@pytest.mark.chaos
@pytest.mark.slow
def test_restart_budget_exhaustion_is_permanent_typed_failure(monkeypatch):
    """Crash-looping rank (killed at op 0, every spawn): one budgeted
    restart happens, the second death exhausts the budget, and the pool
    fails permanently — healthy stays False and every later submit raises
    the typed budget-exhaustion error immediately."""
    pool = _make_pool(monkeypatch, "kill-rank:9@0", budget="1")
    pool.start()
    try:
        async def go():
            with pytest.raises(WorkerDiedError):
                await pool.call(0, None, [30], {}, timeout=None)
            # restart #1 consumes the whole budget; the respawned rank dies
            # again at its op 0 only once something is submitted — the
            # watchdog restarts it, we resubmit, it dies, budget exhausted
            assert _wait_until(lambda: pool.healthy and not pool.recovering)
            with pytest.raises(WorkerDiedError):
                await pool.call(0, None, [30], {}, timeout=None)
            assert _wait_until(lambda: pool.watchdog.failed), \
                "budget exhaustion never flagged"
            assert not pool.healthy
            with pytest.raises(WorkerDiedError) as ei:
                await pool.call(0, None, [0.01], {})
            assert "restart budget exhausted" in str(ei.value)
            assert ei.value.cause == "Killed"

        asyncio.run(go())
        assert "permanent_failure" in pool.watchdog.state_dict()
    finally:
        pool.shutdown()


@pytest.mark.chaos
@pytest.mark.slow
def test_fixed_identity_framework_restarts_full_pool(monkeypatch):
    """JAX/TPU mesh identity is fixed at spawn: one rank dying must respawn
    EVERY rank together (a compiled mesh cannot mix old and new processes),
    per env_contract.per_call_identity."""
    pool = _make_pool(monkeypatch, "kill-rank:9@1", num_procs=2,
                      framework="jax")
    pool.start()
    try:
        async def go():
            await pool.call_all(None, [0.01], {})        # op 0 both ranks
            pids_before = [w.process.pid for w in pool.workers]
            with pytest.raises((WorkerDiedError, Exception)):
                await pool.call_all(None, [30], {}, timeout=None)
            assert _wait_until(lambda: pool.healthy and not pool.recovering)
            pids_after = [w.process.pid for w in pool.workers]
            # full-pool restart: no old pid survives
            assert not set(pids_before) & set(pids_after)
            assert await pool.call_all(None, [0.02], {}) == [0.02, 0.02]

        asyncio.run(go())
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# /ready and /health during recovery
# ---------------------------------------------------------------------------


@pytest.fixture
def bare_server_env(monkeypatch):
    for key in ("KT_CLS_OR_FN_NAME", "KT_MODULE_NAME", "KT_FILE_PATH",
                "KT_DISTRIBUTED_CONFIG", "KT_CHAOS", "POD_IP"):
        monkeypatch.delenv(key, raising=False)
    monkeypatch.setenv("KT_LAUNCH_ID", "wd-1")


def _app():
    from kubetorch_tpu.serving.http_server import create_app
    return create_app()


def test_ready_flaps_during_recovery_and_health_reports_restarts(
        bare_server_env):
    """/ready must be 503 exactly while the watchdog is respawning ranks
    (and forever after permanent failure); /health carries the watchdog's
    restart state."""
    with ThreadedAiohttpServer(_app) as srv:
        state = srv.app["state"]
        stub = SimpleNamespace(
            healthy=True, warming=False, recovering=False, pointers=None,
            restart_state=lambda: {"restarts": 1, "recovering": False,
                                   "budget": 3, "remaining": 2})
        state.supervisor = stub

        assert requests.get(f"{srv.url}/ready", timeout=10).status_code == 200

        stub.recovering = True          # watchdog mid-respawn
        r = requests.get(f"{srv.url}/ready", timeout=10)
        assert r.status_code == 503 and r.json()["recovering"] is True

        stub.recovering = False         # healed: back in the endpoint pool
        assert requests.get(f"{srv.url}/ready", timeout=10).status_code == 200

        stub.healthy = False            # permanent failure: down for good
        assert requests.get(f"{srv.url}/ready", timeout=10).status_code == 503

        health = requests.get(f"{srv.url}/health", timeout=10).json()
        assert health["workers"]["restarts"] == 1
        assert health["workers"]["remaining"] == 2


@pytest.mark.chaos
@pytest.mark.slow
def test_http_end_to_end_kill_recover_ready(monkeypatch):
    """Full-stack acceptance: through the pod server, a mid-call rank kill
    returns a typed 503 WorkerDiedError, /ready goes down during recovery,
    comes back once healed, and the next call succeeds."""
    monkeypatch.setenv("KT_PROJECT_ROOT", ASSETS)
    monkeypatch.setenv("KT_MODULE_NAME", "payloads")
    monkeypatch.setenv("KT_FILE_PATH", "payloads.py")
    monkeypatch.setenv("KT_CLS_OR_FN_NAME", "sleeper")
    monkeypatch.setenv("KT_LAUNCH_ID", "wd-e2e")
    monkeypatch.delenv("KT_DISTRIBUTED_CONFIG", raising=False)
    monkeypatch.delenv("POD_IP", raising=False)
    monkeypatch.setenv("KT_CHAOS", "kill-rank:9@1")
    monkeypatch.setenv("KT_WATCHDOG_INTERVAL_S", "0.25")
    monkeypatch.setenv("KT_RESTART_BUDGET", "3")
    monkeypatch.setenv("KT_RESTART_BACKOFF_BASE_S", "0.01")
    with ThreadedAiohttpServer(_app) as srv:
        r = requests.post(f"{srv.url}/sleeper",
                          json={"args": [0.01], "kwargs": {}}, timeout=60)
        assert r.status_code == 200, r.text

        r = requests.post(f"{srv.url}/sleeper",
                          json={"args": [60], "kwargs": {}}, timeout=30)
        assert r.status_code == 503, r.text
        err = r.json()
        assert err["error_type"] == "WorkerDiedError", err
        assert err["attrs"]["cause"] == "Killed"
        assert err["attrs"]["exitcode"] == -9

        # the kill just landed: the pod must not report ready mid-recovery
        assert requests.get(f"{srv.url}/ready",
                            timeout=10).status_code == 503

        def ready():
            return requests.get(f"{srv.url}/ready",
                                timeout=10).status_code == 200
        assert _wait_until(ready), "/ready never came back after self-heal"

        r = requests.post(f"{srv.url}/sleeper",
                          json={"args": [0.02], "kwargs": {}}, timeout=60)
        assert r.status_code == 200, r.text
        health = requests.get(f"{srv.url}/health", timeout=10).json()
        assert health["workers"]["restarts"] >= 1
